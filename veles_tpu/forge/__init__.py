"""Forge: model-zoo package distribution (reference: veles/forge/ — 1.7k LoC
Tornado site + Twisted client for fetch/upload/list/details/delete of workflow
packages with manifest.json, versioned storage, reference:
veles/forge/forge_client.py:91, forge_server.py:462).

TPU-native rebuild keeps the capability — publish/fetch versioned workflow
packages (the export/package.py serving artifact plus manifest metadata) over
HTTP — with a stdlib-only implementation: a directory-backed versioned store,
a ThreadingHTTPServer, and a urllib client."""

from .store import ForgeStore, Manifest
from .server import ForgeServer
from .client import ForgeClient

__all__ = ["ForgeStore", "Manifest", "ForgeServer", "ForgeClient"]
