"""Forge client: fetch/upload/list/details/delete against a ForgeServer.

Reference parity: veles/forge/forge_client.py:91 (ForgeClient with Twisted
HTTP actions fetch :101, upload :147, list :298, details :338, delete :396).
The rebuild uses stdlib urllib — the client is synchronous because package
transfer is not on any training hot path.
"""

from __future__ import annotations

import json
import os
import urllib.error
import urllib.parse
import urllib.request
from typing import Dict, List, Optional

from ..logger import Logger
from .store import ForgeStore, Manifest


class ForgeClientError(RuntimeError):
    pass


class ForgeClient(Logger):
    def __init__(self, base_url: str):
        self.base_url = base_url.rstrip("/")

    # -- HTTP plumbing -----------------------------------------------------
    # Transient failures (connection refused/reset, 5xx) retry with the
    # backoff + jitter shape shared with the deploy snapshot watcher
    # (runtime/deploy.py http_retry, root.common.net.http_retries); 4xx
    # fail fast — a missing package stays missing no matter how often we
    # ask, and retrying an upload against a validation error re-sends
    # the whole tar for nothing.
    def _retrying(self, do, what: str):
        from ..runtime.deploy import http_retry  # late: keeps the forge
        try:                                     # client import-light
            return http_retry(do, what=what, log=self)
        except urllib.error.HTTPError as e:
            raise ForgeClientError(self._err(e)) from e

    def _get(self, path: str, **params) -> bytes:
        qs = urllib.parse.urlencode(
            {k: v for k, v in params.items() if v is not None})
        url = f"{self.base_url}/{path}" + (f"?{qs}" if qs else "")

        def do():
            with urllib.request.urlopen(url) as resp:
                return resp.read()

        return self._retrying(do, f"GET {url}")

    def _post(self, path: str, body: bytes) -> dict:
        url = f"{self.base_url}/{path}"

        def do():
            req = urllib.request.Request(
                url, data=body,
                headers={"Content-Type": "application/x-gzip"})
            with urllib.request.urlopen(req) as resp:
                return json.loads(resp.read())

        return self._retrying(do, f"POST {url}")

    @staticmethod
    def _err(e: urllib.error.HTTPError) -> str:
        try:
            return json.loads(e.read())["error"]
        except Exception:  # noqa: BLE001
            return f"HTTP {e.code}"

    # -- actions (the reference's ACTIONS table) ---------------------------
    def list(self) -> List[dict]:
        return json.loads(self._get("service", query="list"))

    def details(self, name: str) -> dict:
        return json.loads(self._get("service", query="details", name=name))

    def delete(self, name: str) -> None:
        self._get("service", query="delete", name=name)
        self.info("deleted %s from %s", name, self.base_url)

    def fetch(self, name: str, dest: str,
              version: Optional[str] = None) -> str:
        """Download a package version and unpack it into ``dest`` (reference:
        forge_client.py:101-133 fetched + untarred)."""
        data = self._get("fetch", name=name, version=version)
        ForgeStore.unpack(data, dest)
        self.info("fetched %s -> %s", name, dest)
        return dest

    def upload(self, path: str, manifest: Dict) -> dict:
        """Package a directory + manifest and upload (reference:
        forge_client.py:147-296 streamed metadata + tar)."""
        Manifest.validate(manifest)
        body = ForgeStore.pack_dir(path, manifest)
        out = self._post("upload", body)
        self.info("uploaded %s==%s", out["stored"], out["version"])
        return out

    def upload_workflow(self, workflow, wstate, manifest: Dict,
                        work_dir: str) -> dict:
        """Convenience: export the serving package for ``workflow`` into
        ``work_dir`` and upload it with the manifest."""
        from ..export.package import export_package
        os.makedirs(work_dir, exist_ok=True)
        export_package(workflow, wstate, work_dir, servable=False)
        man = dict(manifest)
        man.setdefault("workflow", "contents.json")
        man.setdefault("configuration", "contents.json")
        return self.upload(work_dir, man)
