"""Versioned package store backing the Forge server.

Reference parity: the reference ForgeServer kept packages in per-name git
repositories with manifest.json metadata and tag-per-version semantics
(reference: veles/forge/forge_server.py:462+, version discovery in
FetchHandler._discover_version :259-283). The rebuild keeps the observable
contract — names, monotonically addable versions, "master" = latest,
manifest metadata, tar.gz package bodies — on a plain directory tree::

    <root>/<name>/<version>/manifest.json + package files
    <root>/<name>/versions.json            (ordered version list)

which is trivially inspectable and needs no git dependency.

Payload-agnostic: an ``export_package()`` directory, a compiled
artifact (``export_compiled()`` — artifact.json + StableHLO programs +
tensors.npz), or any other file set uploads via :meth:`ForgeStore.
pack_dir` and serves back byte-identical; the deploy control plane's
``forge://<root>/<name>[@version]`` sources dispatch on the payload
(runtime/deploy.py: contents.json -> package, artifact.json ->
compiled artifact).
"""

from __future__ import annotations

import io
import json
import os
import re
import shutil
import tarfile
import threading
import time
from typing import Dict, List, Optional

from ..logger import Logger

#: Manifest keys the reference required at upload (forge_server.py upload
#: validation; manifest fields used by the client at forge_client.py:161-182).
REQUIRED_MANIFEST_KEYS = ("name", "workflow", "configuration")
LATEST = "master"  # the reference's "master" version alias

# First char must be alphanumeric/underscore: rejects ".", "..", and other
# dot-only names that would resolve to the store root or its parent.
_NAME_RE = re.compile(r"^[A-Za-z0-9_][A-Za-z0-9_.-]*$")


class Manifest(dict):
    """manifest.json contents; a dict with validation."""

    @classmethod
    def validate(cls, data: dict) -> "Manifest":
        for key in REQUIRED_MANIFEST_KEYS:
            if key not in data:
                raise ValueError(f"manifest misses required key {key!r}")
        if not _NAME_RE.match(str(data["name"])):
            raise ValueError(f"invalid package name {data['name']!r}")
        return cls(data)


class ForgeStore(Logger):
    """Thread-safe versioned package store on a directory tree."""

    def __init__(self, root_dir: str):
        self.root_dir = root_dir
        os.makedirs(root_dir, exist_ok=True)
        self._lock = threading.Lock()

    # -- queries -----------------------------------------------------------
    def list(self) -> List[dict]:
        """[{name, author, short_description, version, updated}] for every
        package (reference: ServiceHandler.handle_list,
        forge_server.py:127-138)."""
        out = []
        for name in sorted(os.listdir(self.root_dir)):
            versions = self._versions(name)
            if not versions:
                continue
            man = self.manifest(name, versions[-1])
            out.append({
                "name": name,
                "author": man.get("author", ""),
                "short_description": man.get("short_description", ""),
                "version": versions[-1],
                "versions": versions,
                "updated": man.get("_uploaded", ""),
            })
        return out

    def details(self, name: str) -> dict:
        """Full manifest of the latest version + version history (reference:
        ServiceHandler.handle_details, forge_server.py:123-126)."""
        versions = self._versions(name)
        if not versions:
            raise KeyError(f"no such package {name!r}")
        man = dict(self.manifest(name, versions[-1]))
        man["versions"] = versions
        return man

    def manifest(self, name: str, version: str) -> Manifest:
        path = os.path.join(self._vdir(name, version), "manifest.json")
        with open(path) as f:
            return Manifest(json.load(f))

    def version_dir(self, name: str,
                    version: Optional[str] = None) -> str:
        """Filesystem directory of a stored version (``version=None`` /
        ``"master"`` resolves to the latest) — the deploy control
        plane's load-by-version hook: an ``export_package()`` directory
        uploaded to the store serves straight from here via
        ``forge://<store_root>/<name>[@version]`` sources
        (runtime/deploy.py)."""
        return self._vdir(name, self.resolve_version(name, version))

    def resolve_version(self, name: str, version: Optional[str]) -> str:
        versions = self._versions(name)
        if not versions:
            raise KeyError(f"no such package {name!r}")
        if version in (None, "", LATEST):
            return versions[-1]
        if version not in versions:
            raise KeyError(f"{name!r} has no version {version!r} "
                           f"(has {versions})")
        return version

    # -- mutation ----------------------------------------------------------
    def add(self, tar_bytes: bytes) -> Manifest:
        """Ingest an uploaded package: a tar.gz whose root contains
        manifest.json (+ workflow/config/package files). Returns the stored
        manifest. Version comes from the manifest ("version" key, default
        autoincrement 1,2,3... as strings)."""
        with io.BytesIO(tar_bytes) as bio, \
                tarfile.open(fileobj=bio, mode="r:*") as tar:
            names = tar.getnames()
            if "manifest.json" not in names:
                raise ValueError("package tar misses manifest.json")
            man = Manifest.validate(json.load(
                tar.extractfile("manifest.json")))
            name = man["name"]
            with self._lock:
                versions = self._versions(name)
                version = str(man.get("version") or len(versions) + 1)
                if version in versions:
                    raise ValueError(
                        f"{name!r} already has version {version!r}")
                vdir = self._vdir(name, version)
                # Extract into a temp dir and rename into place: a rejected
                # upload must not leave partial files that a later upload of
                # the same version would silently serve.
                tmpdir = vdir + ".ingest"
                if os.path.exists(tmpdir):
                    shutil.rmtree(tmpdir)
                os.makedirs(tmpdir)
                try:
                    for member in tar.getmembers():
                        if not member.isfile():
                            continue
                        # thumbnail.svg is SERVER-derived: never accept
                        # an uploaded one (it would be served verbatim
                        # as image/svg+xml — stored-XSS vector when
                        # regeneration fails)
                        if os.path.basename(member.name) == \
                                self.THUMBNAIL:
                            continue
                        # refuse path escapes in hostile archives
                        target = os.path.realpath(
                            os.path.join(tmpdir, member.name))
                        if not target.startswith(
                                os.path.realpath(tmpdir) + os.sep):
                            raise ValueError(
                                f"unsafe member path {member.name!r}")
                        os.makedirs(os.path.dirname(target), exist_ok=True)
                        with tar.extractfile(member) as src, \
                                open(target, "wb") as dst:
                            shutil.copyfileobj(src, dst)
                    man["version"] = version
                    man["_uploaded"] = time.strftime("%Y-%m-%d %H:%M:%S")
                    with open(os.path.join(tmpdir, "manifest.json"),
                              "w") as f:
                        json.dump(man, f, indent=1)
                    # catalog thumbnail (reference: forge_server.py
                    # upload() rendered the workflow graph to
                    # thumbnail.png via PIL/graphviz; here a
                    # dependency-free SVG of the unit chain). Failure
                    # must never reject the upload.
                    try:
                        self._render_thumbnail(tmpdir, man)
                    except Exception as e:  # noqa: BLE001
                        self.warning("thumbnail generation failed for "
                                     "%s: %s", name, e)
                    # An unregistered vdir can exist if a previous process
                    # died between rename and _write_versions; it is orphan
                    # garbage (never listed/served), safe to replace.
                    if os.path.exists(vdir):
                        shutil.rmtree(vdir)
                    os.rename(tmpdir, vdir)
                except Exception:
                    shutil.rmtree(tmpdir, ignore_errors=True)
                    raise
                self._write_versions(name, versions + [version])
        self.info("stored %s==%s", name, version)
        return man

    def delete(self, name: str) -> None:
        """Remove a package entirely (reference: handle_delete,
        forge_server.py:139-152)."""
        path = os.path.join(self.root_dir, name)
        if not os.path.isdir(path):
            raise KeyError(f"no such package {name!r}")
        with self._lock:
            shutil.rmtree(path)
        self.info("deleted %s", name)

    THUMBNAIL = "thumbnail.svg"

    def thumbnail_path(self, name: str,
                       version: Optional[str] = None) -> str:
        """Path of a stored version's catalog thumbnail (KeyError if the
        package/version is unknown; the file may still be absent when
        generation failed — callers 404 on that)."""
        version = self.resolve_version(name, version)
        return os.path.join(self._vdir(name, version), self.THUMBNAIL)

    @classmethod
    def _render_thumbnail(cls, vdir: str, man: Dict) -> None:
        """Write thumbnail.svg: a unit-chain rendering of the package.

        The reference shelled out to `veles --workflow-graph` and PIL to
        produce a 256px PNG per upload (forge_server.py:690-725); the
        rebuild renders a plain SVG with zero dependencies.  Structure
        source, in order of preference: an exported serving package's
        contents.json (unit classes), else the manifest's workflow/
        configuration entries as a two-box summary.
        """
        labels = []
        cj = None
        for base, _, files in os.walk(vdir):
            if "contents.json" in files:
                cj = os.path.join(base, "contents.json")
                break
        if cj is not None:
            with open(cj) as f:
                doc = json.load(f)
            labels = [u.get("name") or u.get("class", "unit")
                      for u in doc.get("units", [])]
        if not labels:
            labels = [str(man.get("workflow", "workflow")),
                      str(man.get("configuration", "config"))]
        more = len(labels) - 10
        if more > 0:
            labels = labels[:9] + [f"... +{more + 1} more"]
        W, bh, gap, pad = 256, 22, 10, 8
        H = pad * 2 + len(labels) * bh + (len(labels) - 1) * gap
        from html import escape as esc
        parts = [f'<svg xmlns="http://www.w3.org/2000/svg" '
                 f'width="{W}" height="{H}" font-family="monospace" '
                 f'font-size="11">',
                 f'<rect width="{W}" height="{H}" fill="#fafafa"/>']
        for i, lab in enumerate(labels):
            y = pad + i * (bh + gap)
            parts.append(
                f'<rect x="28" y="{y}" width="200" height="{bh}" '
                f'rx="4" fill="#e8eef7" stroke="#4a6da7"/>')
            parts.append(
                f'<text x="{W // 2}" y="{y + bh - 7}" '
                f'text-anchor="middle">{esc(str(lab)[:28])}</text>')
            if i + 1 < len(labels):
                ay = y + bh
                parts.append(
                    f'<line x1="{W // 2}" y1="{ay}" x2="{W // 2}" '
                    f'y2="{ay + gap}" stroke="#4a6da7" '
                    f'marker-end="none"/>')
        parts.append("</svg>")
        with open(os.path.join(vdir, cls.THUMBNAIL), "w") as f:
            f.write("".join(parts))

    # -- package IO --------------------------------------------------------
    def pack(self, name: str, version: Optional[str] = None) -> bytes:
        """tar.gz of a stored version (what /fetch streams; reference:
        FetchHandler.get, forge_server.py:284-307)."""
        version = self.resolve_version(name, version)
        vdir = self._vdir(name, version)
        bio = io.BytesIO()
        with tarfile.open(fileobj=bio, mode="w:gz") as tar:
            for fname in sorted(os.listdir(vdir)):
                if fname == self.THUMBNAIL:
                    continue  # server-side derived, not package content
                tar.add(os.path.join(vdir, fname), arcname=fname)
        return bio.getvalue()

    @staticmethod
    def pack_dir(path: str, manifest: Dict) -> bytes:
        """Client-side: build an uploadable tar.gz from a directory plus a
        manifest dict (the reference built the tar from workflow + config +
        extra files listed in the manifest, forge_client.py:147-192)."""
        man = Manifest.validate(manifest)
        bio = io.BytesIO()
        with tarfile.open(fileobj=bio, mode="w:gz") as tar:
            mbytes = json.dumps(man, indent=1).encode()
            info = tarfile.TarInfo("manifest.json")
            info.size = len(mbytes)
            tar.addfile(info, io.BytesIO(mbytes))
            for dirpath, _, files in os.walk(path):
                for fname in sorted(files):
                    if fname == "manifest.json":
                        continue
                    full = os.path.join(dirpath, fname)
                    tar.add(full, arcname=os.path.relpath(full, path))
        return bio.getvalue()

    @staticmethod
    def unpack(tar_bytes: bytes, dest: str) -> str:
        from ..downloader import safe_extract_tar
        os.makedirs(dest, exist_ok=True)
        with io.BytesIO(tar_bytes) as bio, \
                tarfile.open(fileobj=bio, mode="r:*") as tar:
            # "data" filter also rejects symlink members escaping dest —
            # the bytes come from a remote forge server and are untrusted.
            safe_extract_tar(tar, dest)
        return dest

    # -- internals ---------------------------------------------------------
    def _vdir(self, name: str, version: str) -> str:
        if not _NAME_RE.match(name) or not _NAME_RE.match(version):
            raise ValueError(f"invalid name/version {name!r}/{version!r}")
        return os.path.join(self.root_dir, name, version)

    def _versions(self, name: str) -> List[str]:
        path = os.path.join(self.root_dir, name, "versions.json")
        if not os.path.exists(path):
            return []
        with open(path) as f:
            return json.load(f)

    def _write_versions(self, name: str, versions: List[str]) -> None:
        with open(os.path.join(self.root_dir, name, "versions.json"),
                  "w") as f:
            json.dump(versions, f)
