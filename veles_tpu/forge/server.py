"""Forge HTTP server.

Reference parity: veles/forge/forge_server.py — a Tornado site with
``/service?query=list|details|delete``, ``/fetch?name=&version=`` (tar
stream) and ``/upload?version=`` (metadata + tar body) endpoints plus an HTML
catalog page. The rebuild serves the same endpoint contract on a stdlib
``ThreadingHTTPServer`` so it runs anywhere (including inside tests on a
loopback port) with zero dependencies; the HTML frontend is reduced to a
minimal package listing page.
"""

from __future__ import annotations

import html
import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qs, urlparse

from ..logger import Logger
from .store import ForgeStore

SERVICE = "service"
FETCH = "fetch"
UPLOAD = "upload"


class _Handler(BaseHTTPRequestHandler):
    # set by ForgeServer
    store: ForgeStore = None

    def log_message(self, fmt, *args):  # route into our logger
        self.server.owner.debug(fmt, *args)

    # -- helpers -----------------------------------------------------------
    def _json(self, obj, code=200):
        body = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _error(self, code, message):
        self._json({"error": message}, code=code)

    # -- routes ------------------------------------------------------------
    def do_GET(self):
        url = urlparse(self.path)
        q = {k: v[0] for k, v in parse_qs(url.query).items()}
        path = url.path.strip("/")
        try:
            if path == SERVICE:
                self._service(q)
            elif path == FETCH:
                data = self.store.pack(q["name"], q.get("version"))
                self.send_response(200)
                self.send_header("Content-Type", "application/x-gzip")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)
            elif path == "thumbnail":
                self._thumbnail(q)
            elif path == "details.html":
                self._details_page(q["name"])
            elif path in ("", "index.html"):
                self._index()
            else:
                self._error(404, f"unknown path /{path}")
        except KeyError as e:
            self._error(404, str(e))
        except Exception as e:  # noqa: BLE001 — server must answer
            self._error(500, f"{type(e).__name__}: {e}")

    def do_POST(self):
        url = urlparse(self.path)
        if url.path.strip("/") != UPLOAD:
            return self._error(404, "POST only supported on /upload")
        try:
            length = int(self.headers.get("Content-Length", 0))
            man = self.store.add(self.rfile.read(length))
            self._json({"stored": man["name"], "version": man["version"]})
        except ValueError as e:
            self._error(400, str(e))
        except Exception as e:  # noqa: BLE001
            self._error(500, f"{type(e).__name__}: {e}")

    def _service(self, q):
        query = q.get("query")
        if query == "list":
            self._json(self.store.list())
        elif query == "details":
            self._json(self.store.details(q["name"]))
        elif query == "delete":
            self.store.delete(q["name"])
            self._json({"deleted": q["name"]})
        else:
            self._error(400, f"unknown service query {query!r}")

    def _html(self, body: str):
        data = body.encode()
        self.send_response(200)
        self.send_header("Content-Type", "text/html")
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def _index(self):
        rows = "".join(
            f"<tr><td><a href=\"/details.html?name="
            f"{html.escape(p['name'])}\">{html.escape(p['name'])}</a>"
            f"</td><td>{html.escape(p['version'])}</td>"
            f"<td>{html.escape(p['author'])}</td>"
            f"<td>{html.escape(p['short_description'])}</td></tr>"
            for p in self.store.list())
        self._html(
            f"<html><head><title>veles-tpu forge</title></head><body>"
            f"<h1>veles-tpu forge</h1><table border=1>"
            f"<tr><th>name</th><th>version</th><th>author</th>"
            f"<th>description</th></tr>{rows}</table>"
            f"</body></html>")

    def _details_page(self, name):
        """Per-package page: full manifest, version history with fetch
        links, and the unit-graph thumbnail (reference: forge.html /
        image.html package pages, forge_server.py:850-865)."""
        man = self.store.details(name)
        versions = man.pop("versions", [])
        rows = "".join(
            f"<tr><th align=left>{html.escape(str(k))}</th>"
            f"<td>{html.escape(str(v))}</td></tr>"
            for k, v in sorted(man.items()) if not k.startswith("_"))
        vlinks = " ".join(
            f"<a href=\"/fetch?name={html.escape(name)}&version="
            f"{html.escape(v)}\">{html.escape(v)}</a>"
            for v in versions)
        self._html(
            f"<html><head><title>{html.escape(name)} — veles-tpu forge"
            f"</title></head><body><h1>{html.escape(name)}</h1>"
            f"<img src=\"/thumbnail?name={html.escape(name)}\" "
            f"alt=\"workflow\" style=\"float:right;border:1px solid "
            f"#ccc\"/>"
            f"<table>{rows}</table>"
            f"<p>versions: {vlinks}</p>"
            f"<p><a href=\"/\">back to catalog</a></p></body></html>")

    def _thumbnail(self, q):
        import os
        path = self.store.thumbnail_path(q["name"], q.get("version"))
        if not os.path.exists(path):
            return self._error(404, "no thumbnail for this package")
        with open(path, "rb") as f:
            data = f.read()
        self.send_response(200)
        self.send_header("Content-Type", "image/svg+xml")
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)


class ForgeServer(Logger):
    """Run a ForgeStore behind HTTP. ``port=0`` binds an ephemeral port
    (tests); the bound port is in ``.port`` after start()."""

    def __init__(self, store: ForgeStore, host: str = "0.0.0.0",
                 port: int = 0):
        self.store = store
        self.host, self.port = host, port
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "ForgeServer":
        handler = type("BoundHandler", (_Handler,), {"store": self.store})
        self._httpd = ThreadingHTTPServer((self.host, self.port), handler)
        self._httpd.owner = self
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="forge-server")
        self._thread.start()
        self.info("forge serving on %s:%d (store %s)",
                  self.host, self.port, self.store.root_dir)
        return self

    def stop(self):
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._thread.join(timeout=5)
            self._httpd = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
