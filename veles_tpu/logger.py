"""Logging mixin + event tracing.

TPU-native re-design of the reference Logger (reference: veles/logger.py:59 —
mixin with colored console, file duplication :~180, MongoDB duplication :210,
``event()`` distributed-trace API :264-289).

Design changes:
  * MongoDB sink is dropped; the ``event()`` timeline is written as JSON-lines
    to a local file (set ``root.common.trace_file``) so it stays greppable and
    feeds the profiler/status tooling without a database.
  * Integrates with ``jax.profiler`` via :class:`TraceContext` for on-device
    profiling instead of ``--sync-run`` style device syncs.
"""

from __future__ import annotations

import json
import logging
import os
import sys
import threading
import time
from typing import Optional

from .config import root

_COLORS = {
    logging.DEBUG: "\033[37m",
    logging.INFO: "\033[92m",
    logging.WARNING: "\033[93m",
    logging.ERROR: "\033[91m",
    logging.CRITICAL: "\033[1;91m",
}
_RESET = "\033[0m"


class _ColorFormatter(logging.Formatter):
    def format(self, record):
        msg = super().format(record)
        if sys.stderr.isatty():
            color = _COLORS.get(record.levelno, "")
            return f"{color}{msg}{_RESET}"
        return msg


_configured = False
_configure_lock = threading.Lock()


def setup_logging(level=logging.INFO, logfile: Optional[str] = None):
    """Configure the root logger once; colored console + optional file copy
    (reference: veles/logger.py:187 redirect_all_logging_to_file)."""
    global _configured
    with _configure_lock:
        rootlog = logging.getLogger()
        if not _configured:
            handler = logging.StreamHandler(sys.stderr)
            handler.setFormatter(_ColorFormatter(
                "%(asctime)s %(levelname).1s %(name)s: %(message)s",
                datefmt="%H:%M:%S"))
            rootlog.addHandler(handler)
            _configured = True
        rootlog.setLevel(level)
        if logfile:
            fh = logging.FileHandler(logfile)
            fh.setFormatter(logging.Formatter(
                "%(asctime)s %(levelname).1s %(name)s: %(message)s"))
            rootlog.addHandler(fh)


class EventTracer:
    """Append-only JSONL event timeline (reference: Logger.event(),
    veles/logger.py:264-289; events were emitted at run begin/end, ZMQ
    send/recv, and epoch boundaries and viewed in the web status server).

    Here the sink is a file; the schema keeps name/kind/timestamp/attrs."""

    def __init__(self, path: str = ""):
        self._path = path
        self._lock = threading.Lock()
        self._fh = None

    def _ensure(self):
        path = self._path or root.common.value("trace_file", "")
        if not path:
            return None
        if self._fh is None or self._path != path:
            self._path = path
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            self._fh = open(path, "a", buffering=1)
        return self._fh

    def emit(self, name: str, kind: str = "single", **attrs):
        with self._lock:
            fh = self._ensure()
            if fh is None:
                return
            rec = {"ts": time.time(), "name": name, "kind": kind}
            rec.update(attrs)
            fh.write(json.dumps(rec, default=repr) + "\n")

    def close(self):
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None


_tracer = EventTracer()


class Logger:
    """Mixin granting ``self.logger`` + ``info/debug/warning/error`` and the
    ``event()`` trace API (reference: veles/logger.py:59,264)."""

    @property
    def logger(self) -> logging.Logger:
        lg = getattr(self, "_logger_", None)
        if lg is None:
            lg = logging.getLogger(type(self).__name__)
            self._logger_ = lg
        return lg

    def debug(self, msg, *args):
        self.logger.debug(msg, *args)

    def info(self, msg, *args):
        self.logger.info(msg, *args)

    def warning(self, msg, *args):
        self.logger.warning(msg, *args)

    def error(self, msg, *args):
        self.logger.error(msg, *args)

    def exception(self, msg, *args):
        self.logger.exception(msg, *args)

    def event(self, name: str, kind: str = "single", **attrs):
        """Emit a timeline event: kind in {"begin", "end", "single"}."""
        _tracer.emit(name, kind, unit=type(self).__name__, **attrs)


class TraceContext:
    """``with TraceContext("train_step"):`` — emits begin/end events and an
    optional jax.profiler StepTraceAnnotation."""

    def __init__(self, name: str, step: Optional[int] = None, **attrs):
        self.name = name
        self.step = step
        self.attrs = attrs
        self._jax_ctx = None

    def __enter__(self):
        _tracer.emit(self.name, "begin", **self.attrs)
        if self.step is not None:
            try:
                import jax.profiler
                self._jax_ctx = jax.profiler.StepTraceAnnotation(
                    self.name, step_num=self.step)
                self._jax_ctx.__enter__()
            except Exception:  # profiling must never break training
                self._jax_ctx = None
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        dt = time.perf_counter() - self._t0
        if self._jax_ctx is not None:
            self._jax_ctx.__exit__(*exc)
        _tracer.emit(self.name, "end", seconds=dt, **self.attrs)
        return False
