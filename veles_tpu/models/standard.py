"""StandardWorkflow: build a full training workflow from a config layer
list.

Reference parity: Znicz's ``StandardWorkflow`` wired
loader→forwards→evaluator→decision→gradient-units→plotters from a config
layer list (reference: docs manualrst_veles_workflow_creation.rst;
SURVEY.md §2.10). Here gradient units don't exist (autodiff), so the factory
wires loader→forwards→evaluator and pairs with a Trainer.

Layer dicts: ``{"type": "conv_relu", "n_kernels": 96, "kx": 11, ...}``;
``type`` resolves through LAYER_TYPES. The per-layer ``hyperparams`` key
lands in the optimizer's per-unit table (per-layer lr/momentum/l2 —
reference item docs manualrst_veles_algorithms.rst:166).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

from ..ops.optimizers import HyperParams, OPTIMIZERS, Optimizer
from ..units import nn, parallel_nn, recurrent
from ..units.workflow import Workflow

LAYER_TYPES = {
    # parallelism-aware units (sp/pp/ep as config-constructible features)
    "attention": parallel_nn.MultiHeadAttention,
    "moe": parallel_nn.MoEFFN,
    "pipeline_stack": parallel_nn.PipelineStack,
    # recurrent family (reference: Znicz RNN/LSTM "created but not
    # tested", manualrst_veles_algorithms.rst:115-134 — here tested)
    "rnn": recurrent.RNN,
    "gru": recurrent.GRU,
    "lstm": recurrent.LSTM,
    "all2all": nn.All2All,
    "all2all_tanh": nn.All2AllTanh,
    "all2all_relu": nn.All2AllRELU,
    "all2all_sincos": nn.All2AllSincos,
    "softmax": nn.All2AllSoftmax,
    "conv": nn.Conv,
    "conv_relu": nn.ConvRELU,
    "conv_tanh": nn.ConvTanh,
    "deconv": nn.Deconv,
    "max_pooling": nn.MaxPooling,
    "avg_pooling": nn.AvgPooling,
    "stochastic_abs_pooling": nn.StochasticAbsPooling,
    "depool": nn.Depool,
    "dropout": nn.Dropout,
    "lrn": nn.LRN,
    "norm": nn.MeanDispNormalizer,
    "flatten": nn.Flatten,
    "reshape": nn.Reshape,
    "embedding": nn.Embedding,
    "ffn": nn.FFN,
    "layer_norm": nn.LayerNorm,
    "seq_last": nn.SeqLast,
}


# layer-type prefixes that take a compute_dtype kwarg (the MXU-bf16
# switch); shared with PipelineStack's stage-config builder
COMPUTE_DTYPE_TYPES = ("all2all", "softmax", "conv", "deconv", "rnn",
                       "gru", "lstm", "attention", "ffn")


def build_workflow(name: str, layers: Sequence[dict], *,
                   loss: str = "softmax",
                   compute_dtype: Optional[str] = None) -> Workflow:
    """Construct a Workflow from a layer-config list.

    ``loss``: "softmax" -> EvaluatorSoftmax on (@labels, @mask);
              "mse"     -> EvaluatorMSE on (@targets, @mask);
              "mse_input" -> EvaluatorMSE against @input (autoencoders).
    """
    wf = Workflow(name)
    prev = "@input"
    for i, spec in enumerate(layers):
        spec = dict(spec)
        ltype = spec.pop("type")
        spec.pop("hyperparams", None)
        lname = spec.pop("name", f"l{i}_{ltype}")
        # activation rematerialization knob: the training forward wraps
        # this unit in jax.checkpoint, recomputing its internals in the
        # backward instead of taping them (HBM-for-FLOPs trade — the
        # standard lever for deep stacks that don't fit; numerics are
        # identical, tests/test_workflow.py asserts grad exactness).
        # pipeline_stack bodies are ALREADY rematerialized by both
        # schedules — an outer checkpoint would recompute stages twice
        # for no memory benefit, so the flag is dropped there.
        remat = bool(spec.pop("remat", False)) \
            and ltype != "pipeline_stack"
        klass = LAYER_TYPES[ltype]
        if compute_dtype is not None and ltype.startswith(
                COMPUTE_DTYPE_TYPES + ("pipeline_stack",)):
            # pipeline_stack forwards compute_dtype into its stage
            # sublists (only to unit types that take it)
            spec.setdefault("compute_dtype", compute_dtype)
        unit = klass(name=lname, inputs=(prev,), **spec)
        unit.remat = remat
        wf.add(unit)
        prev = lname

    if loss == "softmax":
        wf.add(nn.EvaluatorSoftmax(name="evaluator",
                                   inputs=(prev, "@labels", "@mask")))
    elif loss == "mse":
        wf.add(nn.EvaluatorMSE(name="evaluator",
                               inputs=(prev, "@targets", "@mask")))
    elif loss == "mse_input":
        wf.add(nn.EvaluatorMSE(name="evaluator",
                               inputs=(prev, "@input", "@mask")))
    elif loss == "none":
        pass
    else:
        raise ValueError(f"unknown loss {loss!r}")
    return wf


def build_optimizer(kind: str, layers: Sequence[dict],
                    **kwargs) -> Optimizer:
    """Optimizer from name + per-layer hyperparams gathered off the layer
    configs (the reference's per-gradient-unit settings).

    ``lr_policy`` may be a config dict — ``{"type": "exp"|"inv"|"step"|
    "fixed", ...args}`` — resolved through ops.optimizers.LR_POLICIES, so
    JSON workflow configs can express the reference's lr adjust policies
    (docs manualrst_veles_algorithms.rst:156 item 3)."""
    policy = kwargs.get("lr_policy")
    if isinstance(policy, dict):
        import inspect

        from ..ops.optimizers import LR_POLICIES
        p = dict(policy)
        ptype = p.pop("type")
        if "base" not in p and "lr" not in kwargs:
            # fall back to the optimizer's OWN lr default (AdaDelta is
            # 1.0, Adam 1e-3 — a flat 0.01 would silently rescale them)
            sig = inspect.signature(OPTIMIZERS[kind]).parameters.get("lr")
            if sig is not None and sig.default is not inspect.Parameter.empty:
                p["base"] = sig.default
        p.setdefault("base", kwargs.get("lr", 0.01))
        kwargs["lr_policy"] = LR_POLICIES[ptype](**p)
    per_unit: Dict[str, HyperParams] = {}
    for i, spec in enumerate(layers):
        hp = spec.get("hyperparams")
        if hp:
            lname = spec.get("name", f"l{i}_{spec['type']}")
            per_unit[lname] = HyperParams(**hp) \
                if isinstance(hp, dict) else hp
    return OPTIMIZERS[kind](per_unit=per_unit, **kwargs)


class StandardWorkflow:
    """Convenience bundle: workflow + optimizer + decision settings from one
    config dict (the shape of a reference "workflow config" file)."""

    def __init__(self, config: dict):
        self.config = dict(config)
        layers = self.config["layers"]
        self.workflow = build_workflow(
            self.config.get("name", "StandardWorkflow"), layers,
            loss=self.config.get("loss", "softmax"),
            compute_dtype=self.config.get("compute_dtype"))
        okind = self.config.get("optimizer", "momentum")
        oargs = dict(self.config.get("optimizer_args", {}))
        self.optimizer = build_optimizer(okind, layers, **oargs)

    def make_trainer(self, loader, decision=None, snapshotter=None,
                     mesh=None, rule=None):
        from ..runtime import Decision, Trainer
        decision = decision or Decision(
            max_epochs=self.config.get("max_epochs"),
            fail_iterations=self.config.get("fail_iterations", 50))
        return Trainer(self.workflow, loader, self.optimizer, decision,
                       snapshotter, mesh=mesh, rule=rule,
                       pipeline_microbatches=self.config.get(
                           "pipeline_microbatches"),
                       pipeline_interleave=self.config.get(
                           "pipeline_interleave", 1))
