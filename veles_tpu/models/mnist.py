"""MNIST fully-connected workflow ("MnistSimple" parity).

Reference: the Znicz MNIST workflow — FC 784→100(tanh)→10(softmax), target
1.48-1.92 % validation error (reference: docs
manualrst_veles_algorithms.rst:31, manualrst_veles_example.rst:55-57).

Dataset: real MNIST is loaded from local files when present (idx or npz in
VELES_DATA_DIR / common cache paths — this environment has no network
egress, matching the reference's Downloader-at-init semantics,
veles/downloader.py:56). Otherwise the full-size fixed-seed SynthDigits
procedural dataset (models/synth_data.py) stands in: 60k/10k stroke-
rendered digits calibrated so the reference FC bar (<=1.92 % val error) is
meaningful — see BASELINE.md for the measured numbers.
"""

from __future__ import annotations

import gzip
import os
import struct
from typing import Optional, Tuple

import numpy as np

from ..loader.base import TEST, TRAIN, VALID
from ..loader.fullbatch import FullBatchLoader
from ..normalization import NormalizerRegistry
from .standard import StandardWorkflow

DATA_DIRS = [
    os.environ.get("VELES_DATA_DIR", ""),
    os.path.expanduser("~/data/mnist"),
    os.path.expanduser("~/.cache/mnist"),
    "/root/data/mnist",
]


def _read_idx(path: str) -> np.ndarray:
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        magic, = struct.unpack(">I", f.read(4))
        ndim = magic & 0xFF
        dims = struct.unpack(">" + "I" * ndim, f.read(4 * ndim))
        return np.frombuffer(f.read(), np.uint8).reshape(dims)


def load_real_mnist() -> Optional[Tuple[np.ndarray, ...]]:
    for d in DATA_DIRS:
        if not d:
            continue
        npz = os.path.join(d, "mnist.npz")
        if os.path.exists(npz):
            with np.load(npz) as z:
                return (z["x_train"], z["y_train"],
                        z["x_test"], z["y_test"])
        for ext in ("", ".gz"):
            ti = os.path.join(d, "train-images-idx3-ubyte" + ext)
            if os.path.exists(ti):
                return (
                    _read_idx(ti),
                    _read_idx(os.path.join(
                        d, "train-labels-idx1-ubyte" + ext)),
                    _read_idx(os.path.join(
                        d, "t10k-images-idx3-ubyte" + ext)),
                    _read_idx(os.path.join(
                        d, "t10k-labels-idx1-ubyte" + ext)))
    return None


def synthesize_mnist(n_train=60000, n_valid=10000, seed=20260729
                     ) -> Tuple[np.ndarray, ...]:
    """Full-size deterministic SynthDigits (see models/synth_data.py)."""
    from .synth_data import synth_digits
    return synth_digits(n_train, n_valid, seed)


class MnistLoader(FullBatchLoader):
    """Fullbatch MNIST loader: 28x28 uint8 -> flat normalized f32."""

    def __init__(self, minibatch_size=100, validation_ratio=1 / 6,
                 synthetic_ok=True, n_train=60000, n_valid=10000, **kw):
        real = load_real_mnist()
        if real is not None:
            xt, yt, xte, yte = real
            nv = int(len(xt) * validation_ratio)
            data = {TRAIN: xt[nv:], VALID: xt[:nv], TEST: xte}
            labels = {TRAIN: yt[nv:].astype(np.int32),
                      VALID: yt[:nv].astype(np.int32),
                      TEST: yte.astype(np.int32)}
            self.synthetic = False
        elif synthetic_ok:
            xt, yt, xv, yv = synthesize_mnist(n_train, n_valid)
            data = {TRAIN: xt, VALID: xv}
            labels = {TRAIN: yt, VALID: yv}
            self.synthetic = True
        else:
            raise FileNotFoundError("no MNIST data found; set VELES_DATA_DIR")
        data = {k: (v.reshape(len(v), -1).astype(np.float32))
                for k, v in data.items()}
        super().__init__(
            data, labels,
            normalizer=NormalizerRegistry.create(
                "range_linear", source_range=(0, 255), interval=(-1, 1)),
            minibatch_size=minibatch_size, **kw)


MNIST_CONFIG = {
    "name": "MnistWorkflow",
    "layers": [
        {"type": "all2all_tanh", "output_size": 100, "name": "fc_tanh",
         "hyperparams": {"lr_scale": 1.0}},
        {"type": "softmax", "output_size": 10, "name": "fc_softmax"},
    ],
    "loss": "softmax",
    "optimizer": "momentum",
    "optimizer_args": {"lr": 0.03, "momentum": 0.9, "l2": 1e-5},
    "max_epochs": 25,
    "fail_iterations": 25,
}


def mnist_workflow(minibatch_size=100, loader_args=None,
                   **overrides) -> StandardWorkflow:
    cfg = dict(MNIST_CONFIG)
    cfg.update(overrides)
    sw = StandardWorkflow(cfg)
    sw.loader = MnistLoader(minibatch_size=minibatch_size,
                            **(loader_args or {}))
    return sw
