from .standard import StandardWorkflow, build_workflow, LAYER_TYPES
from .mnist import mnist_workflow, MnistLoader
from .cifar import cifar_workflow, CifarLoader
from .alexnet import alexnet_workflow, ImagenetSyntheticLoader
from .autoencoder import mnist_autoencoder_workflow
from .stl import stl_workflow, StlLoader
from .lm import induction_workflow, InductionLoader
