"""ImageNet AlexNet — the flagship/benchmark model (BASELINE.json
north-star: samples/sec/chip on AlexNet, scaling efficiency 1→8 chips).

Reference: the Znicz ImagenetWorkflow (absent submodule; architecture per
the AlexNet caffe config the reference's docs reference). TPU-first
choices: NHWC layout, bf16 compute with f32 master weights/accumulation,
227×227 inputs so conv1 (k11 s4) tiles cleanly, LRN after conv1/conv2 as in
the original.

ImageNet itself cannot live in HBM or be downloaded here; the loader is a
deterministic synthetic ImageNet-shaped stream (the throughput benchmark's
subject is the compute pipeline, not the JPEG decode — the reference's
fullbatch loader likewise pre-staged decoded tensors on device,
veles/loader/fullbatch.py:79)."""

from __future__ import annotations

import numpy as np

from ..loader.base import TRAIN, VALID, Loader
from .standard import StandardWorkflow

ALEXNET_CONFIG = {
    "name": "AlexNet",
    "compute_dtype": "bfloat16",
    "layers": [
        {"type": "conv_relu", "n_kernels": 96, "kx": 11, "stride": 4,
         "padding": "VALID", "name": "conv1"},
        {"type": "lrn", "name": "lrn1"},
        {"type": "max_pooling", "window": 3, "stride": 2, "name": "pool1"},
        {"type": "conv_relu", "n_kernels": 256, "kx": 5, "padding": 2,
         "name": "conv2"},
        {"type": "lrn", "name": "lrn2"},
        {"type": "max_pooling", "window": 3, "stride": 2, "name": "pool2"},
        {"type": "conv_relu", "n_kernels": 384, "kx": 3, "padding": 1,
         "name": "conv3"},
        {"type": "conv_relu", "n_kernels": 384, "kx": 3, "padding": 1,
         "name": "conv4"},
        {"type": "conv_relu", "n_kernels": 256, "kx": 3, "padding": 1,
         "name": "conv5"},
        {"type": "max_pooling", "window": 3, "stride": 2, "name": "pool5"},
        {"type": "all2all_relu", "output_size": 4096, "name": "fc6"},
        {"type": "dropout", "dropout_ratio": 0.5, "name": "drop6"},
        {"type": "all2all_relu", "output_size": 4096, "name": "fc7"},
        {"type": "dropout", "dropout_ratio": 0.5, "name": "drop7"},
        {"type": "softmax", "output_size": 1000, "name": "fc8"},
    ],
    "loss": "softmax",
    "optimizer": "momentum",
    "optimizer_args": {"lr": 0.01, "momentum": 0.9, "l2": 5e-4},
    "max_epochs": 90,
}

INPUT_HW = 227


class ImagenetSyntheticLoader(Loader):
    """Deterministic ImageNet-shaped stream: 227x227x3 f32, 1000 classes.
    Batches are generated on the fly (no dataset residency), modeling the
    reference's streaming fallback for datasets beyond device memory
    (veles/loader/fullbatch.py:164-242)."""

    def __init__(self, minibatch_size=128, n_train=4096, n_valid=512,
                 n_classes=1000, seed=13, **kw):
        super().__init__(minibatch_size=minibatch_size, **kw)
        self.n_train = n_train
        self.n_valid = n_valid
        self.n_classes = n_classes
        self.seed = seed

    def load_data(self):
        self.class_lengths = [0, self.n_valid, self.n_train]

    def fill_minibatch(self, indices, klass):
        rng = np.random.default_rng(
            [self.seed, klass, int(indices[0]) if len(indices) else 0])
        n = len(indices)
        labels = (indices % self.n_classes).astype(np.int32)
        x = rng.standard_normal(
            (n, INPUT_HW, INPUT_HW, 3)).astype(np.float32)
        return {"@input": x, "@labels": labels}


def alexnet_workflow(minibatch_size=128, **overrides) -> StandardWorkflow:
    cfg = dict(ALEXNET_CONFIG)
    cfg.update(overrides)
    sw = StandardWorkflow(cfg)
    sw.loader = ImagenetSyntheticLoader(minibatch_size=minibatch_size)
    return sw
