"""ImageNet AlexNet — the flagship/benchmark model (BASELINE.json
north-star: samples/sec/chip on AlexNet, scaling efficiency 1→8 chips).

Reference: the Znicz ImagenetWorkflow (absent submodule; architecture per
the AlexNet caffe config the reference's docs reference). TPU-first
choices: NHWC layout, bf16 compute with f32 master weights/accumulation,
227×227 inputs so conv1 (k11 s4) tiles cleanly, LRN after conv1/conv2 as in
the original.

ImageNet itself cannot live in HBM or be downloaded here; the loader is a
deterministic synthetic ImageNet-shaped stream (the throughput benchmark's
subject is the compute pipeline, not the JPEG decode — the reference's
fullbatch loader likewise pre-staged decoded tensors on device,
veles/loader/fullbatch.py:79)."""

from __future__ import annotations

import os

import numpy as np

from ..loader.base import TRAIN, VALID, Loader
from .standard import StandardWorkflow

ALEXNET_CONFIG = {
    "name": "AlexNet",
    "compute_dtype": "bfloat16",
    "layers": [
        {"type": "conv_relu", "n_kernels": 96, "kx": 11, "stride": 4,
         "padding": "VALID", "name": "conv1"},
        {"type": "lrn", "name": "lrn1", "method": "auto"},
        {"type": "max_pooling", "window": 3, "stride": 2, "name": "pool1"},
        {"type": "conv_relu", "n_kernels": 256, "kx": 5, "padding": 2,
         "name": "conv2"},
        {"type": "lrn", "name": "lrn2", "method": "auto"},
        {"type": "max_pooling", "window": 3, "stride": 2, "name": "pool2"},
        {"type": "conv_relu", "n_kernels": 384, "kx": 3, "padding": 1,
         "name": "conv3"},
        {"type": "conv_relu", "n_kernels": 384, "kx": 3, "padding": 1,
         "name": "conv4"},
        {"type": "conv_relu", "n_kernels": 256, "kx": 3, "padding": 1,
         "name": "conv5"},
        {"type": "max_pooling", "window": 3, "stride": 2, "name": "pool5"},
        {"type": "all2all_relu", "output_size": 4096, "name": "fc6"},
        {"type": "dropout", "dropout_ratio": 0.5, "name": "drop6"},
        {"type": "all2all_relu", "output_size": 4096, "name": "fc7"},
        {"type": "dropout", "dropout_ratio": 0.5, "name": "drop7"},
        {"type": "softmax", "output_size": 1000, "name": "fc8"},
    ],
    "loss": "softmax",
    "optimizer": "momentum",
    "optimizer_args": {"lr": 0.01, "momentum": 0.9, "l2": 5e-4},
    "max_epochs": 90,
}

INPUT_HW = 227


class ImagenetSyntheticLoader(Loader):
    """Deterministic ImageNet-shaped stream: 227x227x3 f32, 1000 classes.
    Batches are generated on the fly (no dataset residency), modeling the
    reference's streaming fallback for datasets beyond device memory
    (veles/loader/fullbatch.py:164-242)."""

    def __init__(self, minibatch_size=128, n_train=4096, n_valid=512,
                 n_classes=1000, seed=13, **kw):
        super().__init__(minibatch_size=minibatch_size, **kw)
        self.n_train = n_train
        self.n_valid = n_valid
        self.n_classes = n_classes
        self.seed = seed

    def load_data(self):
        self.class_lengths = [0, self.n_valid, self.n_train]

    def fill_minibatch(self, indices, klass):
        rng = np.random.default_rng(
            [self.seed, klass, int(indices[0]) if len(indices) else 0])
        n = len(indices)
        labels = (indices % self.n_classes).astype(np.int32)
        x = rng.standard_normal(
            (n, INPUT_HW, INPUT_HW, 3)).astype(np.float32)
        return {"@input": x, "@labels": labels}


class ImagenetHostLoader(Loader):
    """End-to-end input-pipeline variant: a host-resident uint8 image store
    with per-sample random crop + mirror augmentation on the host (the
    ImageLoader path, reference: veles/loader/image.py:106) and the
    uint8→float mean/disp normalization left ON DEVICE (the first workflow
    unit, backed by the Pallas mean_disp kernel) — so the host does only
    slicing + one memcpy per batch and the VPU does the arithmetic.

    Measures what the round-1 bench skipped: host augmentation + the
    Trainer's prefetch overlap (BASELINE.md staged vs end-to-end rows).
    """

    STORE_HW = 256  # stored image side; random-cropped to INPUT_HW

    def __init__(self, minibatch_size=128, n_train=4096, n_valid=512,
                 n_classes=1000, seed=13, **kw):
        super().__init__(minibatch_size=minibatch_size, **kw)
        self.n_train = n_train
        self.n_valid = n_valid
        self.n_classes = n_classes
        self.seed = seed
        self._store = None
        self._pool = None

    def _executor(self, workers: int):
        # one long-lived pool: per-batch executor create/join would recur
        # every minibatch of the throughput benchmark
        if self._pool is None:
            from concurrent.futures import ThreadPoolExecutor
            self._pool = ThreadPoolExecutor(workers)
        return self._pool

    def load_data(self):
        self._store, _ = _synth_store(self.n_train + self.n_valid,
                                      self.seed)
        self.class_lengths = [0, self.n_valid, self.n_train]

    def fill_minibatch(self, indices, klass):
        hw, out = self.STORE_HW, INPUT_HW
        base = self.n_valid if klass == TRAIN else 0
        rng = np.random.default_rng(
            [self.seed, klass, int(indices[0]) if len(indices) else 0])
        n = len(indices)
        if klass == TRAIN:
            offs = rng.integers(0, hw - out + 1, (n, 2))
            flip = rng.random(n) < 0.5
        else:
            c = (hw - out) // 2
            offs = np.full((n, 2), c)
            flip = np.zeros(n, bool)
        # contiguous-row slicing beats a sliding_window_view fancy gather
        # ~2x (the gather degenerates to element-wise copies); chunk over
        # a thread pool only when the host actually has cores — the
        # slice copies release the GIL (the reference ran loader work on
        # its thread pool likewise)
        idx = np.asarray(indices) + base
        xs = np.empty((n, out, out, 3), np.uint8)

        def fill(lo, hi):
            for i in range(lo, hi):
                oy, ox = offs[i]
                img = self._store[idx[i], oy:oy + out, ox:ox + out]
                xs[i] = img[:, ::-1] if flip[i] else img

        workers = min(8, os.cpu_count() or 1)
        if n >= 128 and workers > 1:
            chunk = -(-n // workers)
            list(self._executor(workers).map(
                lambda lo: fill(lo, min(lo + chunk, n)),
                range(0, n, chunk)))
        else:
            fill(0, n)
        labels = (indices % self.n_classes).astype(np.int32)
        return {"@input": xs, "@labels": labels}


def alexnet_workflow(minibatch_size=128, loader=None,
                     **overrides) -> StandardWorkflow:
    cfg = dict(ALEXNET_CONFIG)
    cfg.update(overrides)
    sw = StandardWorkflow(cfg)
    sw.loader = loader if loader is not None else \
        ImagenetSyntheticLoader(minibatch_size=minibatch_size)
    return sw


def _e2e_config(**overrides) -> dict:
    """AlexNet config with the device-side mean/disp normalize unit
    prepended — shared by BOTH e2e variants so they measure the same
    compute pipeline and differ only in where augmentation runs."""
    cfg = dict(ALEXNET_CONFIG)
    cfg["layers"] = [
        {"type": "norm", "name": "norm0",
         "mean": np.full((INPUT_HW, INPUT_HW, 3), 127.5, np.float32),
         "rdisp": np.full((INPUT_HW, INPUT_HW, 3), 1 / 64.0, np.float32)},
    ] + [dict(l) for l in ALEXNET_CONFIG["layers"]]
    cfg.update(overrides)
    return cfg


def _synth_store(n: int, seed: int = 13):
    """Deterministic synthetic decoded-JPEG store (uint8 256x256x3) +
    labels — the single recipe behind every e2e input-pipeline variant."""
    hw = ImagenetHostLoader.STORE_HW
    rng = np.random.default_rng(seed)
    store = rng.integers(0, 256, (n, hw, hw, 3), np.uint8)
    labels = np.arange(n, dtype=np.int32) % 1000
    return store, labels


def alexnet_e2e_workflow(minibatch_size=128, n_train=4096,
                         **overrides) -> StandardWorkflow:
    """AlexNet fed through the host image path: uint8 batches from
    ImagenetHostLoader, normalized on device by a prepended MeanDisp unit
    (Pallas kernel) — the end-to-end throughput configuration."""
    sw = StandardWorkflow(_e2e_config(**overrides))
    sw.loader = ImagenetHostLoader(minibatch_size=minibatch_size,
                                   n_train=n_train)
    return sw


def alexnet_e2e_device_workflow(minibatch_size=128, n_train=4096,
                                n_valid=512, seed=13,
                                **overrides) -> StandardWorkflow:
    """End-to-end AlexNet on the TPU-native input pipeline: the uint8
    256x256 store lives in HBM (FullBatchAugmentedLoader) and the random
    crop + mirror + mean/disp normalize all run on device — per step the
    host ships indices and a few KB of augmentation descriptors, nothing
    else.  This is the formulation the host-streaming variant
    (alexnet_e2e_workflow) converges to when host->device bandwidth, not
    compute, is the binding constraint."""
    from ..loader.base import TRAIN, VALID
    from ..loader.fullbatch import FullBatchAugmentedLoader

    sw = StandardWorkflow(_e2e_config(**overrides))
    store, labels = _synth_store(n_train + n_valid, seed)
    sw.loader = FullBatchAugmentedLoader(
        {TRAIN: store[n_valid:], VALID: store[:n_valid]},
        {TRAIN: labels[n_valid:], VALID: labels[:n_valid]},
        minibatch_size=minibatch_size, crop_hw=(INPUT_HW, INPUT_HW),
        mirror=True)
    return sw
