"""Full-size, fixed-seed procedural datasets standing in for MNIST/CIFAR.

This environment has no network egress (DNS resolution fails for every
dataset mirror — storage.googleapis.com, s3.amazonaws.com, yann.lecun.com
all unreachable), so the reference's Downloader-at-init path
(reference: veles/downloader.py:56) cannot fetch the real archives. Per
the round-1 verdict's sanctioned fallback, these generators produce
*full-size* deterministic datasets whose difficulty is calibrated so the
reference model-quality bars are meaningful:

* **SynthDigits** — 28x28 grayscale digits rendered from per-class stroke
  skeletons (polylines/arcs) under random affine pose (rotation, scale,
  shear, translation), per-point stroke jitter, stroke-width/intensity
  variation, background noise and clutter. Same splits as MNIST
  (60k train / 10k validation). A linear softmax model must stay *well
  above* the FC bar (non-trivial task) while the reference FC topology
  (784-100tanh-10softmax, docs manualrst_veles_algorithms.rst:31) can
  reach <= 1.92 % validation error — the reference zoo FC bar
  (docs manualrst_veles_example.rst:55-57).

* **SynthShapes** — 32x32 RGB images of 10 parametric shape classes
  (signed-distance-function renders) under random pose, fill/outline
  style, low-contrast coloring, textured low-frequency backgrounds,
  lighting gradients, distractor shapes and noise. CIFAR-10 splits
  (50k train / 10k validation). Calibrated so a pure FC model is poor
  (pose variation defeats it) while the reference conv topology
  (cifar_caffe, docs manualrst_veles_algorithms.rst:52) can reach the
  17.21 % bar.

Everything is float32 numpy with a fixed seed — bit-identical across
machines — and cached as npz under ``~/.cache/veles_tpu/datasets`` keyed
by (name, version, n, seed).
"""

from __future__ import annotations

import os
from typing import Dict, List, Sequence, Tuple

import numpy as np

CACHE_DIR = os.path.join(
    os.path.expanduser(os.environ.get("VELES_CACHE", "~/.cache/veles_tpu")),
    "datasets")

_DIGITS_VERSION = 3  # bump to invalidate caches when the renderer changes
_SHAPES_VERSION = 3


def _publish_cache(path: str, **arrays) -> None:
    """Atomic cache write safe under concurrent cold-cache processes (e.g.
    --workers farm-out): per-process unique temp file, then rename."""
    import tempfile
    os.makedirs(CACHE_DIR, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=CACHE_DIR, suffix=".npz")
    try:
        with os.fdopen(fd, "wb") as f:
            np.savez_compressed(f, **arrays)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


# ---------------------------------------------------------------------------
# SynthDigits: stroke-skeleton digit renderer
# ---------------------------------------------------------------------------

def _arc(cx: float, cy: float, rx: float, ry: float,
         deg0: float, deg1: float, n: int = 14) -> np.ndarray:
    """Polyline approximation of an ellipse arc. Angles in degrees; y is
    down, 0 deg = +x (right), 90 deg = +y (down)."""
    a = np.radians(np.linspace(deg0, deg1, n))
    return np.stack([cx + rx * np.cos(a), cy + ry * np.sin(a)], axis=1)


def _pl(*pts: Tuple[float, float]) -> np.ndarray:
    return np.asarray(pts, np.float64)


def digit_strokes() -> List[List[np.ndarray]]:
    """Per-class stroke skeletons in the unit square (x right, y down)."""
    return [
        # 0 — closed oval
        [_arc(0.5, 0.5, 0.24, 0.34, 0, 360, 22)],
        # 1 — flag + vertical stem
        [_pl((0.36, 0.30), (0.55, 0.14), (0.55, 0.86))],
        # 2 — top hook, diagonal, base bar
        [np.concatenate([
            _arc(0.48, 0.33, 0.22, 0.19, 185, 355, 10),
            _pl((0.69, 0.40), (0.28, 0.84), (0.74, 0.84))])],
        # 3 — two right bumps
        [np.concatenate([
            _arc(0.44, 0.31, 0.23, 0.17, 190, 430, 12),
            _arc(0.44, 0.67, 0.25, 0.19, 280, 530, 12)])],
        # 4 — diagonal+bar, vertical stem
        [_pl((0.58, 0.13), (0.24, 0.62), (0.80, 0.62)),
         _pl((0.63, 0.38), (0.63, 0.90))],
        # 5 — top bar, left drop, bottom bulge
        [np.concatenate([
            _pl((0.73, 0.14), (0.31, 0.14), (0.29, 0.46)),
            _arc(0.47, 0.64, 0.25, 0.21, 250, 480, 12)])],
        # 6 — left sweep into bottom loop
        [np.concatenate([
            _arc(0.60, 0.42, 0.34, 0.34, 250, 180, 8),
            _arc(0.50, 0.66, 0.22, 0.20, 180, 540, 16)])],
        # 7 — top bar, long diagonal
        [_pl((0.26, 0.16), (0.76, 0.16), (0.44, 0.88))],
        # 8 — stacked loops
        [_arc(0.50, 0.32, 0.19, 0.17, 90, 450, 16),
         _arc(0.50, 0.68, 0.23, 0.19, 270, 630, 16)],
        # 9 — top loop with tail
        [np.concatenate([
            _arc(0.47, 0.34, 0.21, 0.19, 0, 360, 16),
            _pl((0.68, 0.34), (0.64, 0.88))])],
    ]


def _segments(strokes: Sequence[np.ndarray]
              ) -> Tuple[np.ndarray, np.ndarray]:
    """Stack stroke polylines into (P,2) points + (S,2) index pairs."""
    pts, pairs, off = [], [], 0
    for s in strokes:
        pts.append(s)
        pairs.extend((off + i, off + i + 1) for i in range(len(s) - 1))
        off += len(s)
    return np.concatenate(pts), np.asarray(pairs, np.int32)


def _render_stroke_batch(points: np.ndarray, pairs: np.ndarray,
                         widths: np.ndarray, size: int) -> np.ndarray:
    """Distance-field rasterization.

    points: (n, P, 2) in pixel coords; pairs: (S, 2) point-index pairs;
    widths: (n,) stroke half-widths in pixels. Returns (n, size, size)
    float32 in [0, 1].
    """
    n = points.shape[0]
    a = points[:, pairs[:, 0]]           # (n, S, 2)
    b = points[:, pairs[:, 1]]
    ab = b - a                            # (n, S, 2)
    ab2 = np.maximum((ab * ab).sum(-1), 1e-12)           # (n, S)
    g = np.stack(np.meshgrid(np.arange(size), np.arange(size),
                             indexing="xy"), axis=-1).astype(np.float32)
    px = g.reshape(-1, 2)                 # (size*size, 2) as (x, y)
    # (n, S, Q, 2) would be huge; loop over segments instead (S is ~20-40).
    dmin = np.full((n, px.shape[0]), np.inf, np.float32)
    for s in range(pairs.shape[0]):
        ap = px[None, :, :] - a[:, s, None, :]            # (n, Q, 2)
        t = np.clip((ap * ab[:, s, None, :]).sum(-1)
                    / ab2[:, s, None], 0.0, 1.0)          # (n, Q)
        proj = a[:, s, None, :] + t[..., None] * ab[:, s, None, :]
        d = np.sqrt(((px[None] - proj) ** 2).sum(-1))
        np.minimum(dmin, d, out=dmin)
    aa = 0.9  # soft-edge width in pixels (antialias)
    img = np.clip((widths[:, None] + aa - dmin) / aa, 0.0, 1.0)
    return img.reshape(n, size, size)


_RENDER_CHUNK = 2048  # PART OF THE DATASET IDENTITY: per-chunk RNG streams
# are seeded at chunk boundaries, so a different chunking produces a
# different (equally valid) dataset — bump _*_VERSION if this changes.


def render_digits(labels: np.ndarray, rng: np.random.Generator,
                  size: int = 28) -> np.ndarray:
    """Render one image per label with random pose/jitter. Returns uint8."""
    chunk = _RENDER_CHUNK
    skel = digit_strokes()
    out = np.empty((len(labels), size, size), np.uint8)
    # Pose/width/intensity nuisances are drawn for ALL samples up front;
    # the per-chunk `local` streams below are seeded at _RENDER_CHUNK
    # boundaries (part of the dataset identity, see above).
    n = len(labels)
    rot = rng.uniform(-0.33, 0.33, n)
    shear = rng.uniform(-0.26, 0.26, n)
    sx = rng.uniform(0.70, 1.12, n)
    sy = rng.uniform(0.70, 1.12, n)
    tx = rng.uniform(-3.0, 3.0, n)
    ty = rng.uniform(-3.0, 3.0, n)
    width = rng.uniform(0.8, 2.1, n)
    inten = rng.uniform(0.60, 1.00, n)
    # smooth per-sample warp (elastic-like): quadratic coordinate bend
    bend = rng.uniform(-0.155, 0.155, (n, 2))
    noise_seed = rng.integers(0, 2 ** 31, n)
    for cls in range(10):
        pts0, pairs = _segments(skel[cls])
        idx = np.nonzero(labels == cls)[0]
        for lo in range(0, len(idx), chunk):
            ii = idx[lo:lo + chunk]
            m = len(ii)
            # jitter skeleton points (wiggly strokes), then affine to pixels
            local = np.random.default_rng(
                int(noise_seed[ii[0]]) ^ (cls << 20) ^ lo)
            p = pts0[None] + local.normal(0, 0.024, (m,) + pts0.shape)
            c, s = np.cos(rot[ii]), np.sin(rot[ii])
            # affine: rotate * shear * scale, about glyph center
            p = p - 0.5
            x = p[..., 0] * sx[ii, None]
            y = p[..., 1] * sy[ii, None]
            # quadratic bend (elastic-like smooth deformation)
            x = x + bend[ii, 0, None] * (y * y - 0.08)
            y = y + bend[ii, 1, None] * (x * x - 0.08)
            x = x + shear[ii, None] * y
            xr = c[:, None] * x - s[:, None] * y
            yr = s[:, None] * x + c[:, None] * y
            px = (xr + 0.5) * (size - 1) + tx[ii, None]
            py = (yr + 0.5) * (size - 1) + ty[ii, None]
            img = _render_stroke_batch(
                np.stack([px, py], -1), pairs, width[ii], size)
            img *= inten[ii, None, None]
            img += local.normal(0, 0.045, img.shape)  # sensor noise
            # clutter: a faint random short bar on ~40 % of images
            mask = local.random(m) < 0.35
            if mask.any():
                k = np.nonzero(mask)[0]
                cx = local.uniform(3, size - 3, len(k))
                cy = local.uniform(3, size - 3, len(k))
                ang = local.uniform(0, np.pi, len(k))
                ln = local.uniform(2, 5, len(k))
                p2 = np.stack([
                    np.stack([cx - np.cos(ang) * ln, cy - np.sin(ang) * ln],
                             -1),
                    np.stack([cx + np.cos(ang) * ln, cy + np.sin(ang) * ln],
                             -1)], axis=1)  # (k, 2, 2)
                bar = _render_stroke_batch(
                    p2, np.asarray([[0, 1]], np.int32),
                    local.uniform(0.5, 0.9, len(k)).astype(np.float32),
                    size)
                img[k] = np.maximum(
                    img[k], bar * local.uniform(
                        0.20, 0.40, (len(k), 1, 1)))
            out[ii] = (np.clip(img, 0, 1) * 255).astype(np.uint8)
    return out


def synth_digits(n_train: int = 60000, n_valid: int = 10000,
                 seed: int = 20260729, cache: bool = True
                 ) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Full-size deterministic digit dataset (MNIST stand-in)."""
    tag = f"synthdigits_v{_DIGITS_VERSION}_{n_train}_{n_valid}_{seed}.npz"
    path = os.path.join(CACHE_DIR, tag)
    if cache and os.path.exists(path):
        with np.load(path) as z:
            return z["xt"], z["yt"], z["xv"], z["yv"]
    rng = np.random.default_rng(seed)
    yt = rng.integers(0, 10, n_train).astype(np.int32)
    yv = rng.integers(0, 10, n_valid).astype(np.int32)
    xt = render_digits(yt, rng)
    xv = render_digits(yv, rng)
    if cache:
        _publish_cache(path, xt=xt, yt=yt, xv=xv, yv=yv)
    return xt, yt, xv, yv


# ---------------------------------------------------------------------------
# SynthShapes: SDF shape renderer (CIFAR-10 stand-in)
# ---------------------------------------------------------------------------

def _shape_sdf(cls: int, x: np.ndarray, y: np.ndarray,
               r: np.ndarray) -> np.ndarray:
    """Signed distance (negative inside) for shape class ``cls`` at
    pose-normalized coords x, y (arrays (..., Q)); r = shape radius."""
    if cls == 0:       # disk
        return np.hypot(x, y) - r
    if cls == 1:       # ring (annulus)
        return np.abs(np.hypot(x, y) - r) - 0.38 * r
    if cls == 2:       # square
        return np.maximum(np.abs(x), np.abs(y)) - r
    if cls == 3:       # equilateral triangle (3 half-planes)
        k = np.sqrt(3.0)
        d1 = y - r * 0.5
        d2 = (-y * 0.5 + x * k / 2) - r * 0.5
        d3 = (-y * 0.5 - x * k / 2) - r * 0.5
        return np.maximum(np.maximum(d1, d2), d3)
    if cls == 4:       # 5-pointed star (angular radius modulation)
        th = np.arctan2(y, x)
        rad = np.hypot(x, y)
        return rad - r * (0.72 + 0.38 * np.cos(5 * th))
    if cls == 5:       # plus / cross
        ax, ay = np.abs(x), np.abs(y)
        w = 0.36 * r
        d_h = np.maximum(ax - r, ay - w)
        d_v = np.maximum(ax - w, ay - r)
        return np.minimum(d_h, d_v)
    if cls == 6:       # crescent (disk minus offset disk)
        d1 = np.hypot(x, y) - r
        d2 = np.hypot(x - 0.55 * r, y) - 0.78 * r
        return np.maximum(d1, -d2)
    if cls == 7:       # diamond (rotated square / L1 ball)
        return (np.abs(x) + np.abs(y)) - r * 1.2
    if cls == 8:       # three parallel bars clipped to a disk
        stripe = np.abs(((y / r) * 2.4 + 1.5) % 1.5 - 0.75) - 0.28
        return np.maximum(stripe * r, np.hypot(x, y) - r)
    if cls == 9:       # T shape (two rectangles)
        top = np.maximum(np.abs(x) - r, np.abs(y + 0.6 * r) - 0.32 * r)
        stem = np.maximum(np.abs(x) - 0.30 * r, np.abs(y - 0.2 * r)
                          - 0.75 * r)
        return np.minimum(top, stem)
    raise ValueError(cls)


def _low_freq_noise(rng: np.random.Generator, n: int, size: int,
                    coarse: int, channels: int = 3) -> np.ndarray:
    """Smooth random fields via bilinear-upsampled coarse noise."""
    c = rng.standard_normal((n, coarse, coarse, channels)).astype(np.float32)
    # bilinear upsample coarse -> size
    xi = np.linspace(0, coarse - 1, size)
    i0 = np.floor(xi).astype(int)
    i1 = np.minimum(i0 + 1, coarse - 1)
    f = (xi - i0).astype(np.float32)
    c = (c[:, i0] * (1 - f[None, :, None, None])
         + c[:, i1] * f[None, :, None, None])
    c = (c[:, :, i0] * (1 - f[None, None, :, None])
         + c[:, :, i1] * f[None, None, :, None])
    return c


def render_shapes(labels: np.ndarray, rng: np.random.Generator,
                  size: int = 32) -> np.ndarray:
    """Render RGB shape images; returns (n, size, size, 3) uint8."""
    chunk = 2 * _RENDER_CHUNK
    n = len(labels)
    out = np.empty((n, size, size, 3), np.uint8)
    # global per-sample nuisances (chunk-independent)
    rot = rng.uniform(0, 2 * np.pi, n)
    rad = rng.uniform(0.28, 0.46, n) * size
    cx = rng.uniform(0.35, 0.65, n) * size
    cy = rng.uniform(0.35, 0.65, n) * size
    aspect = rng.uniform(0.75, 1.3, n)
    fg = rng.uniform(0.15, 1.0, (n, 3)).astype(np.float32)
    outline = rng.random(n) < 0.25            # 25 % outline-only style
    contrast = rng.uniform(0.35, 1.0, n).astype(np.float32)
    noise_seed = rng.integers(0, 2 ** 31, n)
    g = np.stack(np.meshgrid(np.arange(size), np.arange(size),
                             indexing="xy"), axis=-1).astype(np.float32)
    px = g.reshape(-1, 2)                      # (Q, 2) (x, y)
    for lo in range(0, n, chunk):
        ii = np.arange(lo, min(lo + chunk, n))
        m = len(ii)
        local = np.random.default_rng(int(noise_seed[ii[0]]) ^ lo)
        # pose-normalized coordinates
        dx = (px[None, :, 0] - cx[ii, None])
        dy = (px[None, :, 1] - cy[ii, None])
        c, s = np.cos(rot[ii, None]), np.sin(rot[ii, None])
        xr = (c * dx + s * dy) * aspect[ii, None]
        yr = -s * dx + c * dy
        sd = np.empty((m, px.shape[0]), np.float32)
        for cls in range(10):
            k = np.nonzero(labels[ii] == cls)[0]
            if len(k):
                sd[k] = _shape_sdf(cls, xr[k], yr[k], rad[ii][k, None])
        edge = 1.0
        alpha = np.clip((-sd) / edge + 0.5, 0.0, 1.0)    # fill coverage
        ol = np.clip((1.6 - np.abs(sd)) / edge, 0.0, 1.0)  # outline band
        cover = np.where(outline[ii, None], ol, alpha)   # (m, Q)
        # background: low-frequency colored texture + lighting gradient
        bg = _low_freq_noise(local, m, size, coarse=4) * 0.22
        bg += _low_freq_noise(local, m, size, coarse=8) * 0.12
        base = local.uniform(0.1, 0.9, (m, 1, 1, 3)).astype(np.float32)
        gx = local.uniform(-0.25, 0.25, (m, 1, 1, 1)).astype(np.float32)
        gy = local.uniform(-0.25, 0.25, (m, 1, 1, 1)).astype(np.float32)
        ramp = (gx * (g[None, ..., :1] / size - 0.5)
                + gy * (g[None, ..., 1:] / size - 0.5))
        bg = np.clip(base + bg + ramp, 0.0, 1.0)
        # distractor: a small faint disk on ~35 % of images
        dmask = local.random(m) < 0.35
        if dmask.any():
            k = np.nonzero(dmask)[0]
            dcx = local.uniform(0.1, 0.9, len(k)) * size
            dcy = local.uniform(0.1, 0.9, len(k)) * size
            drr = local.uniform(0.06, 0.14, len(k)) * size
            dd = np.hypot(px[None, :, 0] - dcx[:, None],
                          px[None, :, 1] - dcy[:, None]) - drr[:, None]
            dal = np.clip(-dd + 0.5, 0, 1)[..., None]
            dcol = local.uniform(0, 1, (len(k), 1, 3)).astype(np.float32)
            flat = bg[k].reshape(len(k), -1, 3)
            flat = flat * (1 - 0.6 * dal) + dcol * 0.6 * dal
            bg[k] = flat.reshape(len(k), size, size, 3)
        # composite: low-contrast blend of fg color over bg
        covi = cover.reshape(m, size, size, 1)
        col = fg[ii, None, None, :] * contrast[ii, None, None, None] \
            + bg * (1 - contrast[ii, None, None, None])
        img = bg * (1 - covi) + col * covi
        img += local.normal(0, 0.045, img.shape)
        out[ii] = (np.clip(img, 0, 1) * 255).astype(np.uint8)
    return out


def synth_shapes(n_train: int = 50000, n_valid: int = 10000,
                 seed: int = 20260730, cache: bool = True, size: int = 32
                 ) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Full-size deterministic shape dataset (CIFAR-10 stand-in; at
    ``size=96`` with STL-10 split sizes it is the STL-10 stand-in —
    see models/stl.py)."""
    tag = (f"synthshapes_v{_SHAPES_VERSION}_{n_train}_{n_valid}_{seed}"
           + (f"_s{size}" if size != 32 else "") + ".npz")
    path = os.path.join(CACHE_DIR, tag)
    if cache and os.path.exists(path):
        with np.load(path) as z:
            return z["xt"], z["yt"], z["xv"], z["yv"]
    rng = np.random.default_rng(seed)
    yt = rng.integers(0, 10, n_train).astype(np.int32)
    yv = rng.integers(0, 10, n_valid).astype(np.int32)
    xt = render_shapes(yt, rng, size=size)
    xv = render_shapes(yv, rng, size=size)
    if cache:
        _publish_cache(path, xt=xt, yt=yt, xv=xv, yv=yv)
    return xt, yt, xv, yv
