"""MNIST autoencoder (non-classification path).

Reference: Znicz MNIST AE, validation RMSE target 0.5478 (reference: docs
manualrst_veles_algorithms.rst:71) — an all2all tanh bottleneck trained
with MSE against the input."""

from __future__ import annotations

from .mnist import MnistLoader
from .standard import StandardWorkflow

MNIST_AE_CONFIG = {
    "name": "MnistAutoencoder",
    "layers": [
        {"type": "all2all_tanh", "output_size": 100, "name": "enc"},
        {"type": "all2all", "output_size": 784, "name": "dec",
         "activation": "linear"},
    ],
    "loss": "mse_input",
    "optimizer": "adadelta",
    "optimizer_args": {"lr": 1.0},
    "max_epochs": 20,
    "fail_iterations": 20,
}


def mnist_autoencoder_workflow(minibatch_size=100, loader_args=None,
                               **overrides) -> StandardWorkflow:
    cfg = dict(MNIST_AE_CONFIG)
    cfg.update(overrides)
    sw = StandardWorkflow(cfg)
    sw.loader = MnistLoader(minibatch_size=minibatch_size,
                            **(loader_args or {}))
    return sw
