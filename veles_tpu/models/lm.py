"""Sequence model family: induction-recall task + causal attention LM.

The reference has no sequence models in core (RNN/LSTM existed only as
untested Znicz units — SURVEY.md §5.7, docs
manualrst_veles_algorithms.rst:115-134); long context is first-class in
this rebuild, so this module gives the attention stack a *trainable,
config-driven* model family with a quality bar of its own.

**SynthInduction** — the classic induction-head probe: each sample is a
token sequence whose LAST token repeats an earlier token; the label is
the token that FOLLOWED that earlier occurrence.  Solving it requires
attending from the last position back to the previous occurrence and
reading its successor — a two-attention-layer circuit.  Position-free
models (FC over the flattened sequence can memorize nothing useful at
these sizes) sit near chance = 1/vocab, so the bar is meaningful:

    bar: <= 5 % validation error (chance: 93.75 % error at vocab=16)

Everything is fixed-seed numpy, cached like the other procedural sets.
"""

from __future__ import annotations

import numpy as np

from ..loader.base import TRAIN, VALID
from ..loader.fullbatch import FullBatchLoader
from .standard import StandardWorkflow


def synth_induction(n_train: int = 20000, n_valid: int = 4000,
                    seq_len: int = 32, vocab: int = 16,
                    seed: int = 20260732):
    """Token sequences (n, T) int32 + labels (n,): induction recall."""
    rng = np.random.default_rng(seed)
    n = n_train + n_valid
    x = rng.integers(0, vocab, (n, seq_len)).astype(np.int32)
    # the trigger token appears at position p, its successor at p+1, and
    # again as the final token; the model must emit that successor
    p = rng.integers(0, seq_len - 2, n)
    rows = np.arange(n)
    trigger = x[rows, p]
    # make the trigger UNIQUE elsewhere (else duplicate occurrences with
    # different successors would make labels ambiguous — irreducible
    # error, not a harder task): re-draw clashing positions with a
    # shifted value, which stays in-vocab and != trigger
    clash = x == trigger[:, None]
    x[clash] = (x[clash] + 1 + rng.integers(
        0, vocab - 1, int(clash.sum()))) % vocab
    x[rows, p] = trigger
    x[rows, -1] = trigger
    y = x[rows, p + 1].astype(np.int32)
    return (x[:n_train], y[:n_train], x[n_train:], y[n_train:])


def synth_repeat(n: int, seq_len: int, vocab: int, seed: int = 20260733):
    """Repeated-segment sequences: a random filler prefix, then a random
    segment ``u`` twice — every position of the SECOND copy is
    predictable by 'find the previous occurrence, copy its successor'
    (dense induction signal; random trigger sequences carry it at only
    ~2 of T positions, which is why attention stacks stall on them at
    larger T).  The segment length — hence the repeat offset — VARIES
    per sample: with a fixed offset a RoPE model learns a positional
    copy head (train loss -> 0, zero recall transfer, observed); varied
    offsets force content matching, the actual induction circuit.

    Returns (x, y, m): tokens, next-token labels, and the trainable-
    position mask (second copy only)."""
    rng = np.random.default_rng(seed)
    T = seq_len
    x = rng.integers(0, vocab, (n, T)).astype(np.int32)
    y = np.zeros((n, T), np.int32)
    m = np.zeros((n, T), np.float32)
    # Short segments whose FIRST copy sits anywhere before the tail
    # copy: the source→copy match distance then spans ~[2, T-2]. (A
    # contiguous-[u,u] variant only trains distances ≤ T/2, and the
    # trigger task's matches reach T-2 — the untrained long-distance
    # half dominated the residual error.)
    max_l = max(2, min(16, T // 4))
    lens = rng.integers(2, max_l + 1, n)
    for i in range(n):
        L = int(lens[i])
        a = int(rng.integers(0, T - 2 * L + 1))   # first-copy start
        u = x[i, a:a + L]
        x[i, T - L:] = u                          # tail copy
        y[i, :-1] = x[i, 1:]
        # the last position's induction answer: the token after the
        # SOURCE copy (x[a+L] — for j < L-1 the shift labels already
        # agree with the copy structure)
        y[i, -1] = x[i, a + L] if a + L < T - L else u[0]
        m[i, T - L:] = 1.0
    return x, y, m


class InductionLoader(FullBatchLoader):
    """``per_position=True`` switches to next-token LM training: labels
    are the one-step shift and the loss is per-position CE.  The TRAIN
    split then uses repeated-segment sequences (``synth_repeat``) with
    the mask covering the predictable second half — dense induction
    signal — while VALID keeps the trigger-recall task with the mask on
    ONLY the last position, so the Decision's ``error_pct`` still
    measures pure induction recall (the family's quality bar)."""

    def __init__(self, minibatch_size=100, n_train=20000, n_valid=4000,
                 seq_len=32, vocab=16, per_position=False,
                 repeat_fraction=0.5, data_seed=None, **kw):
        # per_position replaces the synth_induction train half below;
        # regenerating with n_train=0 would change the (seeded) valid
        # slice, so the one-time ~0.2 s is kept for reproducibility
        xt, yt, xv, yv = synth_induction(n_train, n_valid, seq_len, vocab)
        self.per_position = bool(per_position)
        self._train_mask = None
        if self.per_position:
            # curriculum mixture in one dataset, expressed purely via
            # per-sample masks: ``repeat_fraction`` varied-offset
            # repeated segments (dense generic copy signal — forms the
            # induction circuit) and the rest trigger-task sequences
            # supervised at the last position only (the evaluation
            # distribution — consolidates the circuit on arbitrary
            # trigger placements). Phase the fractions via snapshot
            # restore for a sequential curriculum (repeats first).
            if not 0.0 <= float(repeat_fraction) <= 1.0:
                raise ValueError(
                    f"repeat_fraction={repeat_fraction} must be in [0, 1]")
            n_rep = int(n_train * float(repeat_fraction))
            # data_seed varies the REPEAT half across curriculum phases
            # (fresh samples per phase); the trigger/valid sets keep the
            # fixed benchmark seed
            xr, yr, mr = synth_repeat(
                n_rep, seq_len, vocab,
                **({"seed": int(data_seed)} if data_seed is not None
                   else {}))
            xg, yg = xt[:n_train - n_rep], yt[:n_train - n_rep]
            yg = np.concatenate([xg[:, 1:], yg[:, None]], axis=1)
            mg = np.zeros((len(xg), seq_len), np.float32)
            mg[:, -1] = 1.0
            xt = np.concatenate([xr, xg])
            yt = np.concatenate([yr, yg])
            self._train_mask = np.concatenate([mr, mg])
            yv = np.concatenate([xv[:, 1:], yv[:, None]], axis=1)
        super().__init__({TRAIN: xt, VALID: xv},
                         {TRAIN: yt, VALID: yv},
                         minibatch_size=minibatch_size, **kw)
        self.vocab = vocab
        self.seq_len = seq_len

    def make_batch(self, chunk, klass):
        batch = super().make_batch(chunk, klass)
        if self.per_position:
            pad = np.asarray(batch["@mask"], np.float32)  # (bs,)
            m = np.repeat(pad[:, None], self.seq_len, axis=1)
            if klass == TRAIN:
                # train on the induction-predictable second copy only
                # (per-sample extent — the repeat offset varies). chunk
                # is the UNPADDED index list; pad it like super() does
                # (pad rows index row 0 and are zeroed by `pad` anyway).
                idx = np.zeros(self.minibatch_size, np.int64)
                idx[:len(chunk)] = chunk
                m = m * self._train_mask[idx]
            else:
                m[:, :-1] = 0.0  # metric = last-position recall only
            batch["@mask"] = m
        return batch


INDUCTION_CONFIG = {
    "name": "InductionLM",
    "layers": [
        {"type": "embedding", "vocab": 16, "dim": 64, "name": "emb"},
        {"type": "attention", "n_heads": 4, "rope": True,
         "residual": True, "name": "attn1"},
        {"type": "attention", "n_heads": 4, "rope": True,
         "residual": True, "name": "attn2"},
        {"type": "seq_last", "name": "last"},
        {"type": "softmax", "output_size": 16, "name": "out"},
    ],
    "loss": "softmax",
    "optimizer": "adam",
    "optimizer_args": {"lr": 1e-3},
    "max_epochs": 25,
    "fail_iterations": 25,
}


def induction_workflow(minibatch_size=100, loader_args=None,
                       **overrides) -> StandardWorkflow:
    cfg = dict(INDUCTION_CONFIG)
    cfg.update(overrides)
    sw = StandardWorkflow(cfg)
    sw.loader = InductionLoader(minibatch_size=minibatch_size,
                                **(loader_args or {}))
    return sw
