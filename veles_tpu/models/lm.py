"""Sequence model family: induction-recall task + causal attention LM.

The reference has no sequence models in core (RNN/LSTM existed only as
untested Znicz units — SURVEY.md §5.7, docs
manualrst_veles_algorithms.rst:115-134); long context is first-class in
this rebuild, so this module gives the attention stack a *trainable,
config-driven* model family with a quality bar of its own.

**SynthInduction** — the classic induction-head probe: each sample is a
token sequence whose LAST token repeats an earlier token; the label is
the token that FOLLOWED that earlier occurrence.  Solving it requires
attending from the last position back to the previous occurrence and
reading its successor — a two-attention-layer circuit.  Position-free
models (FC over the flattened sequence can memorize nothing useful at
these sizes) sit near chance = 1/vocab, so the bar is meaningful:

    bar: <= 5 % validation error (chance: 93.75 % error at vocab=16)

Everything is fixed-seed numpy, cached like the other procedural sets.
"""

from __future__ import annotations

import numpy as np

from ..loader.base import TRAIN, VALID
from ..loader.fullbatch import FullBatchLoader
from .standard import StandardWorkflow


def synth_induction(n_train: int = 20000, n_valid: int = 4000,
                    seq_len: int = 32, vocab: int = 16,
                    seed: int = 20260732):
    """Token sequences (n, T) int32 + labels (n,): induction recall."""
    rng = np.random.default_rng(seed)
    n = n_train + n_valid
    x = rng.integers(0, vocab, (n, seq_len)).astype(np.int32)
    # the trigger token appears at position p, its successor at p+1, and
    # again as the final token; the model must emit that successor
    p = rng.integers(0, seq_len - 2, n)
    rows = np.arange(n)
    trigger = x[rows, p]
    # make the trigger UNIQUE elsewhere (else duplicate occurrences with
    # different successors would make labels ambiguous — irreducible
    # error, not a harder task): re-draw clashing positions with a
    # shifted value, which stays in-vocab and != trigger
    clash = x == trigger[:, None]
    x[clash] = (x[clash] + 1 + rng.integers(
        0, vocab - 1, int(clash.sum()))) % vocab
    x[rows, p] = trigger
    x[rows, -1] = trigger
    y = x[rows, p + 1].astype(np.int32)
    return (x[:n_train], y[:n_train], x[n_train:], y[n_train:])


class InductionLoader(FullBatchLoader):
    def __init__(self, minibatch_size=100, n_train=20000, n_valid=4000,
                 seq_len=32, vocab=16, **kw):
        xt, yt, xv, yv = synth_induction(n_train, n_valid, seq_len, vocab)
        super().__init__({TRAIN: xt, VALID: xv},
                         {TRAIN: yt, VALID: yv},
                         minibatch_size=minibatch_size, **kw)
        self.vocab = vocab
        self.seq_len = seq_len


INDUCTION_CONFIG = {
    "name": "InductionLM",
    "layers": [
        {"type": "embedding", "vocab": 16, "dim": 64, "name": "emb"},
        {"type": "attention", "n_heads": 4, "rope": True,
         "residual": True, "name": "attn1"},
        {"type": "attention", "n_heads": 4, "rope": True,
         "residual": True, "name": "attn2"},
        {"type": "seq_last", "name": "last"},
        {"type": "softmax", "output_size": 16, "name": "out"},
    ],
    "loss": "softmax",
    "optimizer": "adam",
    "optimizer_args": {"lr": 1e-3},
    "max_epochs": 25,
    "fail_iterations": 25,
}


def induction_workflow(minibatch_size=100, loader_args=None,
                       **overrides) -> StandardWorkflow:
    cfg = dict(INDUCTION_CONFIG)
    cfg.update(overrides)
    sw = StandardWorkflow(cfg)
    sw.loader = InductionLoader(minibatch_size=minibatch_size,
                                **(loader_args or {}))
    return sw
