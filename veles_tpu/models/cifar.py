"""CIFAR-10 convolutional workflow ("cifar_caffe" parity).

Reference: Znicz CIFAR conv net, 17.21 % validation error target
(reference: docs manualrst_veles_algorithms.rst:52) — a caffe-style
conv32-pool-conv32-pool-conv64-pool-fc stack. Real CIFAR-10 batches load
from local files when present; otherwise the full-size fixed-seed
SynthShapes procedural dataset (models/synth_data.py) stands in — 50k/10k
SDF-rendered shape images calibrated so the conv bar (17.21 % val error)
is meaningful. See BASELINE.md."""

from __future__ import annotations

import os
import pickle
from typing import Optional, Tuple

import numpy as np

from ..loader.base import TEST, TRAIN, VALID
from ..loader.fullbatch import FullBatchLoader
from ..normalization import NormalizerRegistry
from .standard import StandardWorkflow

DATA_DIRS = [
    os.environ.get("VELES_DATA_DIR", ""),
    os.path.expanduser("~/data/cifar-10-batches-py"),
    "/root/data/cifar-10-batches-py",
]


def load_real_cifar() -> Optional[Tuple[np.ndarray, ...]]:
    for d in DATA_DIRS:
        if d and os.path.exists(os.path.join(d, "data_batch_1")):
            xs, ys = [], []
            for i in range(1, 6):
                with open(os.path.join(d, f"data_batch_{i}"), "rb") as f:
                    b = pickle.load(f, encoding="bytes")
                xs.append(b[b"data"])
                ys.extend(b[b"labels"])
            with open(os.path.join(d, "test_batch"), "rb") as f:
                b = pickle.load(f, encoding="bytes")
            xt = np.concatenate(xs).reshape(-1, 3, 32, 32) \
                .transpose(0, 2, 3, 1)
            xte = b[b"data"].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
            return (xt, np.asarray(ys, np.int32),
                    xte, np.asarray(b[b"labels"], np.int32))
    return None


def synthesize_cifar(n_train=50000, n_valid=10000, seed=20260730):
    """Full-size deterministic SynthShapes (see models/synth_data.py)."""
    from .synth_data import synth_shapes
    return synth_shapes(n_train, n_valid, seed)


class CifarLoader(FullBatchLoader):
    def __init__(self, minibatch_size=100, validation_ratio=0.1,
                 n_train=50000, n_valid=10000, **kw):
        real = load_real_cifar()
        if real is not None:
            xt, yt, xte, yte = real
            nv = int(len(xt) * validation_ratio)
            data = {TRAIN: xt[nv:], VALID: xt[:nv], TEST: xte}
            labels = {TRAIN: yt[nv:], VALID: yt[:nv], TEST: yte}
            self.synthetic = False
        else:
            xt, yt, xv, yv = synthesize_cifar(n_train, n_valid)
            data = {TRAIN: xt, VALID: xv}
            labels = {TRAIN: yt, VALID: yv}
            self.synthetic = True
        data = {k: v.astype(np.float32) for k, v in data.items()}
        super().__init__(
            data, labels,
            normalizer=NormalizerRegistry.create("mean_disp"),
            minibatch_size=minibatch_size, **kw)


CIFAR_CONFIG = {
    "name": "CifarWorkflow",
    "layers": [
        {"type": "conv_relu", "n_kernels": 32, "kx": 5, "padding": 2,
         "name": "conv1"},
        {"type": "max_pooling", "window": 3, "stride": 2, "name": "pool1"},
        {"type": "conv_relu", "n_kernels": 32, "kx": 5, "padding": 2,
         "name": "conv2"},
        {"type": "avg_pooling", "window": 3, "stride": 2, "name": "pool2"},
        {"type": "conv_relu", "n_kernels": 64, "kx": 5, "padding": 2,
         "name": "conv3"},
        {"type": "avg_pooling", "window": 3, "stride": 2, "name": "pool3"},
        {"type": "softmax", "output_size": 10, "name": "fc_softmax"},
    ],
    "loss": "softmax",
    "optimizer": "momentum",
    "optimizer_args": {"lr": 0.01, "momentum": 0.9, "l2": 4e-3},
    "max_epochs": 40,
    "fail_iterations": 40,
}


def cifar_workflow(minibatch_size=100, loader_args=None,
                   **overrides) -> StandardWorkflow:
    cfg = dict(CIFAR_CONFIG)
    cfg.update(overrides)
    sw = StandardWorkflow(cfg)
    sw.loader = CifarLoader(minibatch_size=minibatch_size,
                            **(loader_args or {}))
    return sw
