"""STL-10 convolutional workflow.

Reference: the Znicz STL-10 result — 35.10 % validation error
(reference: docs/source/manualrst_veles_algorithms.rst:53), the same
caffe-style conv stack family as the CIFAR workflow applied to 96x96
images with STL-10's small labeled split (5k train / 8k test).  The bar
encodes exactly that difficulty: a conv net trained on only 5k labeled
images.

Dataset: real STL-10 loads from local binary files when present
(train_X.bin / train_y.bin / test_X.bin / test_y.bin in VELES_DATA_DIR or
common cache paths; this environment has no network egress — see
models/synth_data.py).  Otherwise the SynthShapes renderer at 96 px with
STL-10's split sizes stands in: same 10 shape classes and nuisances as the
CIFAR-10 stand-in, but only 5k labeled training images, so generalization
from a small sample — the thing the STL-10 bar measures — is preserved.
"""

from __future__ import annotations

import os
from typing import Optional, Tuple

import numpy as np

from ..loader.base import TEST, TRAIN, VALID
from ..loader.fullbatch import FullBatchLoader
from ..normalization import NormalizerRegistry
from .standard import StandardWorkflow

DATA_DIRS = [
    os.environ.get("VELES_DATA_DIR", ""),
    os.path.expanduser("~/data/stl10_binary"),
    "/root/data/stl10_binary",
]


def load_real_stl10() -> Optional[Tuple[np.ndarray, ...]]:
    """STL-10 binary format: uint8, (N, 3, 96, 96) column-major per
    plane; labels are 1-based."""
    for d in DATA_DIRS:
        if d and os.path.exists(os.path.join(d, "train_X.bin")):
            def imgs(name):
                raw = np.fromfile(os.path.join(d, name), np.uint8)
                return (raw.reshape(-1, 3, 96, 96)
                        .transpose(0, 3, 2, 1))  # -> (N, H, W, C)

            def labels(name):
                return (np.fromfile(os.path.join(d, name), np.uint8)
                        .astype(np.int32) - 1)

            return (imgs("train_X.bin"), labels("train_y.bin"),
                    imgs("test_X.bin"), labels("test_y.bin"))
    return None


def synthesize_stl(n_train=5000, n_valid=8000, seed=20260731):
    """SynthShapes at 96 px with STL-10 split sizes (synth_data.py)."""
    from .synth_data import synth_shapes
    return synth_shapes(n_train, n_valid, seed, size=96)


class StlLoader(FullBatchLoader):
    def __init__(self, minibatch_size=50, n_train=5000, n_valid=8000, **kw):
        real = load_real_stl10()
        if real is not None:
            xt, yt, xte, yte = real
            data = {TRAIN: xt, VALID: xte}
            labels = {TRAIN: yt, VALID: yte}
            self.synthetic = False
        else:
            xt, yt, xv, yv = synthesize_stl(n_train, n_valid)
            data = {TRAIN: xt, VALID: xv}
            labels = {TRAIN: yt, VALID: yv}
            self.synthetic = True
        data = {k: v.astype(np.float32) for k, v in data.items()}
        super().__init__(
            data, labels,
            normalizer=NormalizerRegistry.create("mean_disp"),
            minibatch_size=minibatch_size, **kw)


STL_CONFIG = {
    "name": "StlWorkflow",
    "layers": [
        {"type": "conv_relu", "n_kernels": 32, "kx": 5, "padding": 2,
         "name": "conv1"},
        {"type": "max_pooling", "window": 3, "stride": 2, "name": "pool1"},
        {"type": "conv_relu", "n_kernels": 32, "kx": 5, "padding": 2,
         "name": "conv2"},
        {"type": "avg_pooling", "window": 3, "stride": 2, "name": "pool2"},
        {"type": "conv_relu", "n_kernels": 64, "kx": 5, "padding": 2,
         "name": "conv3"},
        {"type": "avg_pooling", "window": 3, "stride": 2, "name": "pool3"},
        {"type": "all2all_relu", "output_size": 128, "name": "fc4"},
        {"type": "dropout", "dropout_ratio": 0.5, "name": "drop4"},
        {"type": "softmax", "output_size": 10, "name": "fc_softmax"},
    ],
    "loss": "softmax",
    "optimizer": "momentum",
    "optimizer_args": {"lr": 0.01, "momentum": 0.9, "l2": 4e-3},
    "max_epochs": 60,
    "fail_iterations": 60,
}


def stl_workflow(minibatch_size=50, loader_args=None,
                 **overrides) -> StandardWorkflow:
    cfg = dict(STL_CONFIG)
    cfg.update(overrides)
    sw = StandardWorkflow(cfg)
    sw.loader = StlLoader(minibatch_size=minibatch_size,
                          **(loader_args or {}))
    return sw
