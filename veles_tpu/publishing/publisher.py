"""Report gathering (reference: veles/publishing/publisher.py:57 — the
Publisher unit collected workflow name/description, results, image plots,
the workflow graph and environment info, then handed a template context to
a backend)."""

from __future__ import annotations

import dataclasses
import datetime
import getpass
import platform
import socket
from typing import Any, Dict, List, Optional, Sequence

from ..logger import Logger


@dataclasses.dataclass
class Report:
    """Backend-independent template context."""
    title: str
    description: str = ""
    created: str = ""
    host: str = ""
    user: str = ""
    platform: str = ""
    workflow_units: List[str] = dataclasses.field(default_factory=list)
    workflow_checksum: str = ""
    config_dump: str = ""
    results: Dict[str, Any] = dataclasses.field(default_factory=dict)
    metrics: Dict[str, List[float]] = \
        dataclasses.field(default_factory=dict)
    images: List[str] = dataclasses.field(default_factory=list)  # file paths

    def metric_series(self, name: str) -> List[float]:
        return list(self.metrics.get(name, []))


class Publisher(Logger):
    """Gathers a Report from trainer/workflow/recorder, renders via
    backends.

    Usage::

        pub = Publisher("MNIST FC run", backends=[MarkdownBackend("out")])
        pub.gather(trainer=trainer, recorder=recorder)
        paths = pub.publish()
    """

    def __init__(self, title: str, description: str = "", *,
                 backends: Sequence = ()):
        self.report = Report(title=title, description=description)
        self.backends = list(backends)

    def gather(self, *, trainer=None, workflow=None, recorder=None,
               results: Optional[Dict] = None, config=None,
               images: Sequence[str] = ()) -> Report:
        r = self.report
        r.created = datetime.datetime.now().isoformat(timespec="seconds")
        r.host = socket.gethostname()
        try:
            r.user = getpass.getuser()
        except Exception:
            r.user = "unknown"
        r.platform = platform.platform()
        if trainer is not None:
            workflow = workflow or trainer.workflow
            results = results if results is not None else trainer.results
            recorder = recorder or trainer.recorder
        if workflow is not None:
            r.workflow_units = [u.name for u in workflow.units]
            try:
                r.workflow_checksum = workflow.checksum()
            except Exception:
                pass
        if results:
            r.results = {k: v for k, v in results.items()}
        if recorder is not None and getattr(recorder, "series", None):
            r.metrics = {k: list(v) for k, v in recorder.series.items()}
        if config is not None:
            r.config_dump = config.dump() if hasattr(config, "dump") \
                else str(config)
        r.images = list(images)
        return r

    def publish(self) -> List[str]:
        """Render through every backend; returns produced artifact paths
        (URLs for remote backends)."""
        out = []
        for backend in self.backends:
            path = backend.render(self.report)
            self.info("published %r via %s -> %s",
                      self.report.title, type(backend).__name__, path)
            out.append(path)
        return out
