"""Publishing: turn a finished training run into a report.

Reference parity: veles/publishing/ — ``Publisher`` gathered workflow
results, plots and graphs (publisher.py:57) and rendered them through
backend classes: Markdown (markdown_backend.py), PDF (pdf_backend.py),
Confluence wiki (confluence.py), all jinja2-templated. The rebuild keeps
the gather→backend split with zero extra dependencies: Markdown and HTML
are plain string templates, PDF is a minimal self-contained PDF 1.4 writer
(text-only — the reference's PDF path pulled in wkhtmltopdf-class tooling
we don't have), and Confluence posts through its REST API with urllib
(gated: requires a reachable server + token).
"""

from .publisher import Publisher, Report
from .backends import (ConfluenceBackend, HtmlBackend, MarkdownBackend,
                       PdfBackend)

__all__ = ["Publisher", "Report", "MarkdownBackend", "HtmlBackend",
           "PdfBackend", "ConfluenceBackend"]
