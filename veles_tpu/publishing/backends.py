"""Report render backends: Markdown, HTML, PDF, Confluence.

Reference: veles/publishing/{markdown_backend,pdf_backend,confluence}.py.
All dependency-free; see package docstring for the PDF/Confluence scope.
"""

from __future__ import annotations

import html
import json
import os
import urllib.request
from typing import List

from ..logger import Logger
from ..plotting import sparkline
from .publisher import Report


def _fmt_val(v) -> str:
    if isinstance(v, float):
        return f"{v:.6g}"
    return str(v)


class MarkdownBackend(Logger):
    """report.md with results table, metric sparklines, unit list
    (reference: veles/publishing/markdown_backend.py)."""

    def __init__(self, out_dir: str, filename: str = "report.md"):
        self.out_dir = out_dir
        self.filename = filename

    def render_text(self, r: Report) -> str:
        lines = [f"# {r.title}", ""]
        if r.description:
            lines += [r.description, ""]
        lines += [f"*{r.created} — {r.user}@{r.host} — {r.platform}*", ""]
        if r.results:
            lines += ["## Results", "", "| metric | value |", "|---|---|"]
            lines += [f"| {k} | {_fmt_val(v)} |"
                      for k, v in sorted(r.results.items())]
            lines.append("")
        if r.metrics:
            lines += ["## Metrics", "", "```"]
            for name in sorted(r.metrics):
                series = r.metric_series(name)
                if series:
                    lines.append(f"{name:<28} {sparkline(series)} "
                                 f"last={series[-1]:.6g}")
            lines += ["```", ""]
        if r.workflow_units:
            lines += ["## Workflow", "",
                      " → ".join(r.workflow_units),
                      "", f"checksum: `{r.workflow_checksum}`", ""]
        for img in r.images:
            lines.append(f"![plot]({os.path.basename(img)})")
        if r.config_dump:
            lines += ["", "## Configuration", "", "```", r.config_dump,
                      "```"]
        return "\n".join(lines) + "\n"

    def render(self, r: Report) -> str:
        os.makedirs(self.out_dir, exist_ok=True)
        path = os.path.join(self.out_dir, self.filename)
        with open(path, "w") as f:
            f.write(self.render_text(r))
        return path


class HtmlBackend(MarkdownBackend):
    """Standalone HTML page (the reference rendered Markdown to wiki/HTML
    through jinja2; here a minimal converter over the same content)."""

    def __init__(self, out_dir: str, filename: str = "report.html"):
        super().__init__(out_dir, filename)

    def render(self, r: Report) -> str:
        os.makedirs(self.out_dir, exist_ok=True)
        rows = "".join(
            f"<tr><td>{html.escape(str(k))}</td>"
            f"<td>{html.escape(_fmt_val(v))}</td></tr>"
            for k, v in sorted(r.results.items()))
        sparks = "".join(
            f"<div class='spark'><b>{html.escape(n)}</b> "
            f"<code>{html.escape(sparkline(r.metric_series(n)))}</code> "
            f"last={r.metric_series(n)[-1]:.6g}</div>"
            for n in sorted(r.metrics) if r.metric_series(n))
        imgs = "".join(
            f"<img src='{html.escape(os.path.basename(p))}' "
            f"style='max-width:48rem'>" for p in r.images)
        doc = f"""<!doctype html><html><head><meta charset="utf-8">
<title>{html.escape(r.title)}</title>
<style>body{{font-family:sans-serif;margin:2rem auto;max-width:52rem}}
table{{border-collapse:collapse}}td{{border:1px solid #ccc;
padding:.25rem .6rem}}code{{background:#f4f4f4}}</style></head><body>
<h1>{html.escape(r.title)}</h1>
<p>{html.escape(r.description)}</p>
<p><i>{html.escape(r.created)} — {html.escape(r.user)}@{html.escape(r.host)}
</i></p>
<h2>Results</h2><table>{rows}</table>
<h2>Metrics</h2>{sparks}
{imgs}
<h2>Workflow</h2><p>{html.escape(' → '.join(r.workflow_units))}</p>
<pre>{html.escape(r.config_dump)}</pre>
</body></html>"""
        path = os.path.join(self.out_dir, self.filename)
        with open(path, "w") as f:
            f.write(doc)
        return path


class PdfBackend(MarkdownBackend):
    """Minimal text PDF writer — no external tooling. Renders the Markdown
    text line-by-line in Courier (monospace keeps the sparklines and
    tables aligned). Valid PDF 1.4: catalog/pages/page+stream/font objects
    with a correct xref table."""

    def __init__(self, out_dir: str, filename: str = "report.pdf"):
        super().__init__(out_dir, filename)

    @staticmethod
    def _esc(line: str) -> str:
        # Latin-1-safe: PDF literal strings; replace unencodable chars.
        out = line.encode("latin-1", "replace").decode("latin-1")
        return (out.replace("\\", r"\\").replace("(", r"\(")
                .replace(")", r"\)"))

    def _pages(self, text: str, lines_per_page: int = 56) -> List[str]:
        lines = text.splitlines() or [""]
        return ["\n".join(lines[i:i + lines_per_page])
                for i in range(0, len(lines), lines_per_page)]

    def render(self, r: Report) -> str:
        os.makedirs(self.out_dir, exist_ok=True)
        pages = self._pages(self.render_text(r))
        objs: List[bytes] = []  # 1-indexed PDF objects, in order
        n_pages = len(pages)
        # object ids: 1 catalog, 2 pages, 3 font, then (page, stream) pairs
        page_ids = [4 + 2 * i for i in range(n_pages)]
        objs.append(b"<< /Type /Catalog /Pages 2 0 R >>")
        kids = " ".join(f"{pid} 0 R" for pid in page_ids)
        objs.append(f"<< /Type /Pages /Kids [{kids}] "
                    f"/Count {n_pages} >>".encode())
        objs.append(b"<< /Type /Font /Subtype /Type1 "
                    b"/BaseFont /Courier >>")
        for i, page in enumerate(pages):
            body = ["BT /F1 9 Tf 40 780 Td 13 TL"]
            for ln in page.splitlines():
                body.append(f"({self._esc(ln)}) Tj T*")
            body.append("ET")
            stream = "\n".join(body).encode("latin-1")
            objs.append(
                f"<< /Type /Page /Parent 2 0 R /MediaBox [0 0 612 792] "
                f"/Resources << /Font << /F1 3 0 R >> >> "
                f"/Contents {page_ids[i] + 1} 0 R >>".encode())
            objs.append(b"<< /Length " + str(len(stream)).encode() +
                        b" >>\nstream\n" + stream + b"\nendstream")
        buf = bytearray(b"%PDF-1.4\n")
        offsets = [0]
        for i, obj in enumerate(objs, start=1):
            offsets.append(len(buf))
            buf += f"{i} 0 obj\n".encode() + obj + b"\nendobj\n"
        xref_at = len(buf)
        buf += f"xref\n0 {len(objs) + 1}\n".encode()
        buf += b"0000000000 65535 f \n"
        for off in offsets[1:]:
            buf += f"{off:010d} 00000 n \n".encode()
        buf += (f"trailer\n<< /Size {len(objs) + 1} /Root 1 0 R >>\n"
                f"startxref\n{xref_at}\n%%EOF\n").encode()
        path = os.path.join(self.out_dir, self.filename)
        with open(path, "wb") as f:
            f.write(bytes(buf))
        return path


class ConfluenceBackend(Logger):
    """Posts the report as a Confluence page via the REST API (reference:
    veles/publishing/confluence.py used the XML-RPC/SOAP API). Gated on a
    reachable server: construction is free, render() raises a clear error
    when the POST fails."""

    def __init__(self, base_url: str, space: str, *, token: str = "",
                 parent_id: str = "", timeout: float = 30.0):
        self.base_url = base_url.rstrip("/")
        self.space = space
        self.token = token
        self.parent_id = parent_id
        self.timeout = timeout

    def render(self, r: Report) -> str:
        md = MarkdownBackend("", "").render_text(r)
        # A literal "]]>" in the report would terminate the CDATA section;
        # the standard escape splits it across two CDATA sections.
        md = md.replace("]]>", "]]]]><![CDATA[>")
        body_html = f"<ac:structured-macro ac:name=\"code\">" \
                    f"<ac:plain-text-body><![CDATA[{md}]]>" \
                    f"</ac:plain-text-body></ac:structured-macro>"
        payload = {
            "type": "page",
            "title": r.title,
            "space": {"key": self.space},
            "body": {"storage": {"value": body_html,
                                 "representation": "storage"}},
        }
        if self.parent_id:
            payload["ancestors"] = [{"id": self.parent_id}]
        req = urllib.request.Request(
            self.base_url + "/rest/api/content",
            data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json",
                     **({"Authorization": f"Bearer {self.token}"}
                        if self.token else {})},
            method="POST")
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                data = json.loads(resp.read())
        except OSError as e:
            raise IOError(
                f"cannot publish to Confluence at {self.base_url} ({e}); "
                "this environment may have no network egress") from e
        link = data.get("_links", {})
        url = (link.get("base", self.base_url) +
               link.get("webui", f"/pages/{data.get('id', '')}"))
        return url
