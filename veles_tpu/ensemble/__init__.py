from .driver import EnsembleTrainer, EnsembleTester
from .scoring import SweepTimeout, score_candidates
