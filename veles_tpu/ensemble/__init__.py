from .driver import EnsembleTrainer, EnsembleTester
from .scoring import score_candidates
