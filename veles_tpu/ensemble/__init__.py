from .driver import EnsembleTrainer, EnsembleTester
