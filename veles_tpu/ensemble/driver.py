"""Ensemble training/testing.

Reference parity: veles/ensemble/ — train N model instances on random
subsets of the train set (``train_ratio``), collect per-model metric JSON,
then test by weighted vote over the stored snapshots
(base_workflow.py:59-176, model_workflow.py:50-150, test_workflow.py:50-107;
per-model results consumed by veles/loader/ensemble.py:53-143).

Redesign: the reference exec'd a standalone ``veles`` subprocess per model
on each slave; here each member is an in-process training (already
device-parallel), parameterized by (seed, subset)."""

from __future__ import annotations

import json
import os
from typing import Callable, Dict, List, Optional, Sequence

import jax
import numpy as np

from ..logger import Logger
from ..runtime.snapshotter import Snapshotter


class EnsembleTrainer(Logger):
    """Train ``n_models`` members.

    ``member_factory(member_id, seed, train_ratio)`` must return a ready
    Trainer (workflow+loader+optimizer wired); the loader should subsample
    its train set with the given ratio/seed."""

    def __init__(self, member_factory: Callable, n_models: int,
                 train_ratio: float = 0.8, *, base_seed: int = 1000,
                 out_dir: str = "ensemble"):
        self.member_factory = member_factory
        self.n_models = n_models
        self.train_ratio = train_ratio
        self.base_seed = base_seed
        self.out_dir = out_dir
        self.results: List[dict] = []

    def run(self) -> List[dict]:
        os.makedirs(self.out_dir, exist_ok=True)
        for m in range(self.n_models):
            seed = self.base_seed + m
            trainer = self.member_factory(m, seed, self.train_ratio)
            trainer.initialize(seed=seed)
            res = trainer.run()
            snap = Snapshotter(f"member{m}", self.out_dir, interval=1)
            path = snap.save("final", trainer._payload())
            entry = {"id": m, "seed": seed, "snapshot": path,
                     "best_value": trainer.decision.best_value,
                     "results": res}
            self.results.append(entry)
            self.info("member %d/%d: best=%.4f", m + 1, self.n_models,
                      trainer.decision.best_value)
        with open(os.path.join(self.out_dir, "ensemble.json"), "w") as f:
            json.dump(self.results, f, indent=1, default=repr)
        return self.results


class EnsembleTester(Logger):
    """Weighted soft-vote over member snapshots.

    ``workflow_factory()`` returns a fresh (built) workflow matching the
    members; weights default to 1/best_value (better members vote more,
    the reference's weighted voting).

    Snapshots are loaded and the predict step jitted ONCE at construction
    (all members share one compiled function, wstate is an argument)."""

    def __init__(self, workflow_factory: Callable, manifest: str,
                 output_unit: Optional[str] = None):
        with open(manifest) as f:
            self.members = json.load(f)
        wf = workflow_factory()
        self._predict = wf.make_predict_step(output_unit)
        self._wstates = [
            Snapshotter.restore_wstate(Snapshotter.load(m["snapshot"]))
            for m in self.members]

    def predict(self, batch: Dict) -> np.ndarray:
        """Ensemble class probabilities for one batch."""
        votes = None
        total_w = 0.0
        for m, wstate in zip(self.members, self._wstates):
            logits = np.asarray(self._predict(wstate, batch), np.float64)
            p = np.exp(logits - logits.max(-1, keepdims=True))
            p /= p.sum(-1, keepdims=True)
            w = 1.0 / max(float(m.get("best_value", 1.0)), 1e-3)
            votes = p * w if votes is None else votes + p * w
            total_w += w
        return votes / total_w

    def error_rate(self, batches: Sequence[Dict]) -> float:
        """Weighted-vote error over labeled batches (with @mask)."""
        err, n = 0.0, 0.0
        for batch in batches:
            probs = self.predict({"@input": batch["@input"]})
            pred = probs.argmax(-1)
            labels = np.asarray(batch["@labels"])
            mask = np.asarray(batch.get("@mask",
                                        np.ones(len(labels), np.float32)))
            err += float(((pred != labels) * mask).sum())
            n += float(mask.sum())
        return 100.0 * err / max(n, 1.0)
