"""Ensemble training/testing.

Reference parity: veles/ensemble/ — train N model instances on random
subsets of the train set (``train_ratio``), collect per-model metric JSON,
then test by weighted vote over the stored snapshots
(base_workflow.py:59-176, model_workflow.py:50-150, test_workflow.py:50-107;
per-model results consumed by veles/loader/ensemble.py:53-143).

Redesign: the reference exec'd a standalone ``veles`` subprocess per model
on each slave (base_workflow.py:135-143). The rebuild offers both shapes:
in-process members via ``member_factory`` (each training already
device-parallel), and the reference's farm-out via ``cli_argv`` +
``n_workers`` — every member becomes a standalone CLI run on a bounded
subprocess pool (parallel/pool.py), subset/seed injected as inline config
overrides."""

from __future__ import annotations

import json
import os
from typing import Callable, Dict, List, Optional, Sequence

import jax
import numpy as np

from ..logger import Logger
from ..runtime.snapshotter import Snapshotter


class EnsembleTrainer(Logger):
    """Train ``n_models`` members.

    ``member_factory(member_id, seed, train_ratio)`` must return a ready
    Trainer (workflow+loader+optimizer wired); the loader should subsample
    its train set with the given ratio/seed."""

    def __init__(self, member_factory: Optional[Callable], n_models: int,
                 train_ratio: float = 0.8, *, base_seed: int = 1000,
                 out_dir: str = "ensemble", n_workers: int = 1,
                 cli_argv: Optional[Sequence[str]] = None,
                 env: Optional[Dict[str, str]] = None):
        if member_factory is None and cli_argv is None:
            raise ValueError("need member_factory or cli_argv")
        self.member_factory = member_factory
        self.n_models = n_models
        self.train_ratio = train_ratio
        self.base_seed = base_seed
        self.out_dir = out_dir
        self.n_workers = max(int(n_workers), 1)
        self.cli_argv = list(cli_argv) if cli_argv is not None else None
        self.env = env
        self.results: List[dict] = []

    def run(self) -> List[dict]:
        os.makedirs(self.out_dir, exist_ok=True)
        if self.cli_argv is not None:
            self._run_subprocess_members()
        else:
            self._run_inprocess_members()
        with open(os.path.join(self.out_dir, "ensemble.json"), "w") as f:
            json.dump(self.results, f, indent=1, default=repr)
        return self.results

    def _run_inprocess_members(self) -> None:
        for m in range(self.n_models):
            seed = self.base_seed + m
            trainer = self.member_factory(m, seed, self.train_ratio)
            trainer.initialize(seed=seed)
            res = trainer.run()
            snap = Snapshotter(f"member{m}", self.out_dir, interval=1)
            path = snap.save("final", trainer._payload())
            entry = {"id": m, "seed": seed, "snapshot": path,
                     "best_value": trainer.decision.best_value,
                     "results": res}
            self.results.append(entry)
            self.info("member %d/%d: best=%.4f", m + 1, self.n_models,
                      trainer.decision.best_value)

    def _run_subprocess_members(self) -> None:
        """Reference farm-out: each member is a standalone CLI training
        (veles/ensemble/base_workflow.py:135-143) on the worker pool."""
        from ..parallel.pool import CliRunner
        jobs = []
        for m in range(self.n_models):
            seed = self.base_seed + m
            member_dir = os.path.join(self.out_dir, f"member{m}")
            jobs.append([
                *self.cli_argv,
                f"loader.train_ratio={self.train_ratio}",
                f"loader.subset_seed={seed}",
                "--random-seed", str(seed),
                "--snapshot-dir", member_dir,
            ])
        runner = CliRunner(n_workers=self.n_workers, env=self.env)
        for m, res in enumerate(runner.run_jobs(jobs)):
            member_dir = os.path.join(self.out_dir, f"member{m}")
            snap_path = None
            if os.path.isdir(member_dir):
                for link in ("_best.json", "_current.json"):
                    cands = [f for f in os.listdir(member_dir)
                             if f.endswith(link)]
                    if cands:
                        snap_path = os.path.realpath(
                            os.path.join(member_dir, cands[0]))
                        break
            entry = {"id": m, "seed": self.base_seed + m,
                     "snapshot": snap_path,
                     "best_value": res.get("best_value"),
                     "results": res}
            self.results.append(entry)
            if "error" in res:
                self.warning("member %d failed: %s", m,
                             str(res["error"])[:300])
            else:
                self.info("member %d/%d: best=%s", m + 1, self.n_models,
                          res.get("best_value"))


class EnsembleTester(Logger):
    """Weighted soft-vote over member snapshots.

    ``workflow_factory()`` returns a fresh (built) workflow matching the
    members; weights default to 1/best_value (better members vote more,
    the reference's weighted voting).

    Snapshots are loaded and the predict step jitted ONCE at construction
    (all members share one compiled function, wstate is an argument)."""

    def __init__(self, workflow_factory: Callable, manifest: str,
                 output_unit: Optional[str] = None):
        with open(manifest) as f:
            entries = json.load(f)
        # drop failed members (the farm-out records them with
        # snapshot=None rather than aborting the whole training run)
        self.members = [m for m in entries if m.get("snapshot")]
        dropped = len(entries) - len(self.members)
        if dropped:
            Logger.warning(self, "%d member(s) without snapshots skipped",
                           dropped)
        if not self.members:
            raise ValueError(f"no usable members in {manifest}")
        wf = workflow_factory()
        self._predict = wf.make_predict_step(output_unit)
        self._wstates = [
            Snapshotter.restore_wstate(Snapshotter.load(m["snapshot"]))
            for m in self.members]

    def predict(self, batch: Dict) -> np.ndarray:
        """Ensemble class probabilities for one batch."""
        votes = None
        total_w = 0.0
        for m, wstate in zip(self.members, self._wstates):
            logits = np.asarray(self._predict(wstate, batch), np.float64)
            p = np.exp(logits - logits.max(-1, keepdims=True))
            p /= p.sum(-1, keepdims=True)
            bv = m.get("best_value")
            w = 1.0 / max(float(bv if bv is not None else 1.0), 1e-3)
            votes = p * w if votes is None else votes + p * w
            total_w += w
        return votes / total_w

    def error_rate(self, batches: Sequence[Dict]) -> float:
        """Weighted-vote error over labeled batches (with @mask)."""
        err, n = 0.0, 0.0
        for batch in batches:
            probs = self.predict({"@input": batch["@input"]})
            pred = probs.argmax(-1)
            labels = np.asarray(batch["@labels"])
            mask = np.asarray(batch.get("@mask",
                                        np.ones(len(labels), np.float32)))
            err += float(((pred != labels) * mask).sum())
            n += float(mask.sum())
        return 100.0 * err / max(n, 1.0)
