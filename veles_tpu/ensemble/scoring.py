"""Ensemble candidate scoring on the batch lane.

The first real consumer of the job API (``runtime/jobs.py``): an
ensemble eval sweep — N candidate configurations, each with its own
eval prompt set — becomes ONE batch job.  Every prompt rides the
engine's trough-filler class (``"batch": true`` in each dispatched
body), so a sweep over hundreds of candidates runs entirely in the
capacity interactive traffic is not using, yields instantly when a
burst arrives, and survives crashes/drains via the job store's
committed per-prompt results.  Contrast with :class:`~.driver.
EnsembleTester`, which re-runs inference in-process per batch — the
batch lane lets the sweep share a *serving* fleet instead.
"""

from __future__ import annotations

from typing import Callable, List, Optional, Sequence


class SweepTimeout(TimeoutError):
    """An ensemble sweep's job did not reach a terminal state in time.

    Carries ``job_id`` so unattended callers (the experiment manager)
    can cancel or resume the exact job instead of string-parsing the
    message — the job itself keeps running and its committed results
    remain resumable."""

    def __init__(self, job_id: str, timeout_s: float, status: dict):
        super().__init__(
            f"ensemble sweep job {job_id} not terminal after "
            f"{timeout_s}s: {status}")
        self.job_id = job_id
        self.timeout_s = timeout_s
        self.status = status


def score_candidates(jobs, candidates: Sequence[dict],
                     scorer: Callable[[dict, List[dict]], float], *,
                     steps: int = 8, seed: int = 0,
                     temperature: Optional[float] = None,
                     top_k: Optional[int] = None,
                     top_p: Optional[float] = None,
                     eos_id: Optional[int] = None,
                     timeout_s: float = 120.0) -> List[dict]:
    """Score every candidate by running its eval prompts through one
    batch job and handing the committed results to ``scorer``.

    ``jobs`` is a started :class:`~veles_tpu.runtime.jobs.JobManager`;
    ``candidates`` is a sequence of ``{"name": str, "prompts":
    [[token ids], ...]}``; ``scorer(candidate, results)`` maps a
    candidate plus its prompt-ordered result docs (each ``{"index",
    "tokens"}`` or ``{"index", "error"}``) to a float.  All candidate
    prompt lists are flattened into a single job — per-prompt seeds are
    ``seed + flat_index``, so scores are deterministic regardless of
    which replica (or how many retries) served each prompt.  Returns
    one ``{"name", "score", "n_prompts", "job_id"}`` per candidate, in
    input order.
    """
    if not candidates:
        return []
    flat: List[List[int]] = []
    bounds: List[int] = [0]
    for cand in candidates:
        prompts = cand["prompts"]
        if not prompts:
            raise ValueError(
                f"candidate {cand.get('name')!r} has no eval prompts")
        flat.extend(prompts)
        bounds.append(len(flat))
    spec = {"prompts": flat, "steps": int(steps), "seed": int(seed)}
    for k, v in (("temperature", temperature), ("top_k", top_k),
                 ("top_p", top_p), ("eos_id", eos_id)):
        if v is not None:
            spec[k] = v
    doc = jobs.submit(spec)
    job_id = doc["id"]
    if not jobs.wait(job_id, timeout_s=timeout_s):
        raise SweepTimeout(job_id, timeout_s, jobs.status(job_id))
    by_idx = {}
    offset = 0
    while True:
        page = jobs.results(job_id, offset)
        for r in page["results"]:
            by_idx[r["index"]] = r
        if "next_offset" not in page:
            break
        offset = page["next_offset"]
    out: List[dict] = []
    for ci, cand in enumerate(candidates):
        # exactly one doc per prompt, in prompt order: permanent per-prompt
        # failures arrive as the store's committed {"index", "error"} docs,
        # and any index with no committed result at all (job cancelled
        # mid-flight) becomes a synthesized error doc — the scorer sees a
        # deterministic, complete window either way instead of a silently
        # shorter (misaligned) list poisoning the sweep.
        docs = [by_idx.get(i, {"index": i, "error": "no committed result"})
                for i in range(bounds[ci], bounds[ci + 1])]
        out.append({"name": cand.get("name", str(ci)),
                    "score": float(scorer(cand, docs)),
                    "n_prompts": bounds[ci + 1] - bounds[ci],
                    "job_id": job_id})
    return out
