"""The autonomous optimization loop: train → select → hot-swap.

:class:`ExperimentManager` closes the loop the rest of the runtime only
provides pieces of (ROADMAP "fleet-scale experiment manager"): a search
policy (``policies.py``) mints trial configs over the base config's
``Range`` tuneables; each trial is a short training run through
:class:`~veles_tpu.runtime.trainer.Trainer` + the snapshotter; trained
candidates are scored **on the serving fleet** through the batch lane
(:func:`~veles_tpu.ensemble.score_candidates` via ``JobManager``), so
evaluation consumes only slot/SLO headroom and interactive p99 is
untouched; the winner ships through the fleet's two-phase coordinated
swap, gated by an improvement margin over the baseline — all with no
human in the loop.

Durability is the same contract as the batch lane.  Experiment state
lives in an :class:`~.store.ExperimentStore` (fsync-rename commits):
the manifest records spec + coarse state, one file per finished trial
records seed/genome/snapshot/score.  A crashed or drained manager
resumes mid-generation — ``start()`` relaunches every non-terminal
experiment, the drive loop re-proposes each generation (policies are
deterministic from ``(seed, generation)`` + observed scores, the PR's
``generation_rng`` contract), committed trials are never re-run, and
interrupted ones restart from their deterministic per-trial seeds.
Genomes found in committed trials are verified against the replay — a
store that does not match its seed fails loudly instead of silently
mixing two histories.

Trials being materialized register in the ``_claimed`` ledger (the
``experiment-trials`` resource the VR701 pairing rule tracks): claim
before any work, release on commit (:meth:`_commit_trial`) or abort
(:meth:`_abort_trial`), with ``cancel`` and ``stop`` sweeping leftovers.

REST surface (fleet server and single replica): ``POST /experiments``
submit → ``GET /experiments/<id>`` status, ``GET /experiments`` list,
``DELETE /experiments/<id>`` cancel; the fleet merges
:meth:`ExperimentManager.summary` into ``/fleet.json``.  See
docs/experiments.md for the loop anatomy and failure semantics.
"""

from __future__ import annotations

import json
import math
import threading
import time
import uuid
from typing import Callable, Dict, List, Optional, Tuple

from ..config import Config, root
from ..ensemble.scoring import score_candidates
from ..logger import Logger
from ..runtime import faults
from ..runtime.metrics import registry
from ..runtime.snapshotter import Snapshotter
from .policies import POLICIES, SearchPolicy
from .store import ExperimentStore

#: spec keys a ``POST /experiments`` body may carry (anything else is a
#: 400 — a typoed ``"populaton"`` must not silently run the default).
_SPEC_KEYS = frozenset({
    "policy", "generations", "population", "seed", "name",
    "eval_prompts", "eval_steps", "eval_seed", "promote",
})

#: terminal experiment states (the drive thread is gone).
_TERMINAL = ("done", "failed", "cancelled")


class ExperimentError(ValueError):
    """Malformed experiment spec, unusable manager wiring, or a store
    that contradicts its deterministic replay (the REST 400 path)."""


class _Cancelled(Exception):
    """Internal unwind for cancel()/stop(): the drive thread exits
    between trials without writing a terminal state itself — cancel()
    already committed ``cancelled``, and stop() deliberately leaves
    ``running`` on disk for a successor manager to resume."""


def default_scorer(candidate: dict, docs: List[dict]) -> float:
    """Train-metric scoring with serving-side disqualification: the
    score is the trial's ``best_value`` (lower = better, the Decision's
    stopping metric), but any per-prompt ``error`` doc in the sweep —
    the candidate's snapshot failed to serve its eval prompts — scores
    ``inf`` so a candidate that trains well but cannot serve never
    wins.  Replace via the manager's ``scorer=`` hook to score from the
    generated tokens themselves."""
    if any("error" in d for d in docs):
        return math.inf
    bv = (candidate.get("trial") or {}).get("best_value")
    return float(bv) if bv is not None else math.inf


def fleet_promoter(router) -> Callable[[str], dict]:
    """Promotion hook wrapping the fleet's two-phase coordinated swap:
    stage the winner's snapshot on every active replica, commit only
    when all staged, roll back on any failure — the returned dict's
    ``swapped`` False means the old version is still serving
    everywhere (the swap's own atomicity guarantee)."""
    def _promote(snapshot_path: str) -> dict:
        return router.coordinated_swap(source=snapshot_path)
    return _promote


def _genome_key(genome: dict) -> str:
    return json.dumps(genome, sort_keys=True)


class ExperimentManager(Logger):
    """Drives experiments end to end (one daemon thread per experiment).

    ``trial_factory(trial, config) -> Trainer`` builds one trial's
    training run from the materialized config; ``trial`` is a dict of
    ``{"experiment", "generation", "index", "seed", "genome",
    "out_dir"}`` — factories typically derive the data subset and any
    member identity from ``seed``.  With ``cli_argv`` + ``workers > 1``
    trials instead run as standalone CLI trainings on a bounded
    subprocess pool (genome injected as inline ``path=value``
    overrides, the :class:`~veles_tpu.genetics.SubprocessEvaluator`
    shape); in-process sequential is the default — one training already
    fills the device mesh.

    ``jobs`` (a started :class:`~veles_tpu.runtime.jobs.JobManager`)
    plus ``eval_prompts`` turn scoring into a batch-lane sweep on the
    serving fleet; without them, scores fall back to the trials' own
    ``best_value``.  ``promote`` is the promotion hook
    (:func:`fleet_promoter`); None records the winner without swapping.
    """

    def __init__(self, store_dir: Optional[str] = None,
                 trial_factory: Optional[Callable] = None, *,
                 config: Optional[Config] = None,
                 policy_factory: Optional[Callable] = None,
                 jobs=None,
                 promote: Optional[Callable[[str], dict]] = None,
                 scorer: Optional[Callable] = None,
                 eval_prompts: Optional[List[List[int]]] = None,
                 workers: Optional[int] = None,
                 promote_margin: Optional[float] = None,
                 eval_timeout_s: Optional[float] = None,
                 cli_argv: Optional[List[str]] = None,
                 env: Optional[Dict[str, str]] = None):
        exp_cfg = root.common.experiment
        if store_dir is None:
            store_dir = str(exp_cfg.get("dir", "") or "")
            if not store_dir:
                raise ExperimentError(
                    "no experiment store: pass store_dir or set "
                    "root.common.experiment.dir")
        self._store = ExperimentStore(store_dir)
        self.trial_factory = trial_factory
        self.config = config
        self.policy_factory = policy_factory
        self.jobs = jobs
        self.promote_fn = promote
        self.scorer = default_scorer if scorer is None else scorer
        self.eval_prompts = eval_prompts
        self.workers = max(1, int(exp_cfg.get("workers", 1)
                                  if workers is None else workers))
        self.promote_margin = float(
            exp_cfg.get("promote_margin", 0.0)
            if promote_margin is None else promote_margin)
        self.eval_timeout_s = float(
            exp_cfg.get("eval_timeout_s", 300.0)
            if eval_timeout_s is None else eval_timeout_s)
        self.cli_argv = list(cli_argv) if cli_argv is not None else None
        self.env = env
        self._lock = threading.Lock()
        self._exps: Dict[str, dict] = {}        # guarded-by: self._lock
        self._trials: Dict[str, Dict[Tuple[int, int], dict]] = {}  # guarded-by: self._lock
        self._claimed: Dict[Tuple[str, int, int], float] = {}  # guarded-by: self._lock
        self._threads: Dict[str, threading.Thread] = {}  # guarded-by: self._lock
        self._cancelled: set = set()            # guarded-by: self._lock
        self._counts = {"submitted": 0, "completed": 0, "failed": 0,
                        "cancelled": 0}         # guarded-by: self._lock
        self._trial_launches = 0                # guarded-by: self._lock
        self._stop_evt = threading.Event()
        reg = registry()
        self._m_submitted = reg.counter(
            "vt_experiments_submitted_total", "experiments accepted by "
            "POST /experiments (resumed-from-disk ones not re-counted)")
        self._m_completed = reg.counter(
            "vt_experiments_completed_total",
            "experiments that ran their full loop to the done state")
        self._m_trials = reg.counter(
            "vt_experiment_trials_total", "trials actually trained "
            "(committed through the claim ledger, incl. failed ones)")
        self._m_trials_cached = reg.counter(
            "vt_experiment_trials_cached_total", "trials satisfied from "
            "an earlier identical genome (GA elites) without retraining")
        self._m_promotions = reg.counter(
            "vt_experiment_promotions_total", "winners committed to the "
            "fleet via the two-phase coordinated swap")
        self._m_promote_failures = reg.counter(
            "vt_experiment_promote_failures_total", "promotion attempts "
            "whose swap failed or rolled back (old version kept serving)")
        self._g_running = reg.gauge(
            "vt_experiment_running",
            "experiments currently in the running state")
        self._g_best = reg.gauge(
            "vt_experiment_best_score",
            "best (lowest) candidate score of the most recently "
            "finished experiment")
        # crash/drain resume: reload every persisted experiment; the
        # non-terminal ones relaunch on start()
        for man in self._store.load_all():
            self._exps[man["id"]] = man
            self._trials[man["id"]] = self._store.load_trials(man["id"])
        self._g_running.set(sum(
            1 for e in self._exps.values() if e["state"] == "running"))

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "ExperimentManager":
        """Relaunch every persisted non-terminal experiment — the
        crashed/drained-manager resume path.  Completed trials are
        never re-run: each generation is re-proposed deterministically
        and matched against the committed trial files."""
        self._stop_evt.clear()
        with self._lock:
            resumable = [eid for eid, e in self._exps.items()
                         if e["state"] not in _TERMINAL
                         and eid not in self._threads]
        for eid in resumable:
            self.info("resuming experiment %s", eid)
            self._spawn(eid)
        return self

    def stop(self):
        """Drain: stop driving, leave every running experiment's state
        ``running`` on disk — a successor manager (or this one after
        ``start()``) resumes from exactly the committed trials."""
        self._stop_evt.set()
        with self._lock:
            threads = list(self._threads.values())
        for t in threads:
            t.join(timeout=10.0)
        with self._lock:
            stale = list(self._claimed)
        for key in stale:
            self._abort_trial(key)

    def _spawn(self, exp_id: str) -> None:
        with self._lock:
            if exp_id in self._threads:
                return
            t = threading.Thread(target=self._run_experiment,
                                 args=(exp_id,), daemon=True,
                                 name=f"experiment-{exp_id}")
            self._threads[exp_id] = t
        t.start()

    # -- the experiment-trials ledger (analysis RESOURCE_PAIRS) --------------
    def _claim_trial(self, key: Tuple[str, int, int]) -> None:
        """Register one trial being materialized in the ``_claimed``
        ledger.  Every claim MUST reach :meth:`_commit_trial` (result
        committed) or :meth:`_abort_trial` (crash/cancel/shutdown
        paths) — VR701 pins the pairing."""
        with self._lock:
            self._claimed[key] = time.monotonic()

    def _commit_trial(self, key: Tuple[str, int, int], doc: dict) -> None:
        """Durably commit one finished trial, then release its
        ``_claimed`` entry.  The store write lands first: a crash
        between the two leaves a committed trial plus a stale claim the
        exit sweeps drop — never a released claim whose work is lost."""
        exp_id, gen, idx = key
        self._store.commit_trial(exp_id, doc)
        with self._lock:
            self._trials.setdefault(exp_id, {})[(gen, idx)] = doc
            self._claimed.pop(key, None)

    def _abort_trial(self, key: Tuple[str, int, int]) -> None:
        """Release one ``_claimed`` entry without committing (idempotent
        — the cancel and stop sweeps race the drive thread's own
        finally)."""
        with self._lock:
            self._claimed.pop(key, None)

    # -- submission / query API ----------------------------------------------
    def submit(self, spec: dict) -> dict:
        """Validate + persist one experiment, launch its drive thread,
        return the status doc.  The manifest commits BEFORE the thread
        starts: from the client's 200 onward the experiment survives
        any crash and resumes on the next ``start()``."""
        if not isinstance(spec, dict):
            raise ExperimentError("experiment spec must be a JSON object")
        unknown = set(spec) - _SPEC_KEYS
        if unknown:
            raise ExperimentError(
                f"unknown experiment spec keys: {sorted(unknown)}")
        if self.trial_factory is None and self.cli_argv is None:
            raise ExperimentError(
                "this manager cannot launch trials (no trial_factory "
                "or cli_argv attached; see docs/experiments.md)")
        exp_cfg = root.common.experiment
        exp = {
            "id": uuid.uuid4().hex[:12],
            "name": str(spec.get("name") or ""),
            "state": "running",
            "created": time.time(),
            "policy": str(spec.get("policy", "genetic")),
            "generations": int(spec.get(
                "generations", exp_cfg.get("generations", 4))),
            "population": int(spec.get(
                "population", exp_cfg.get("population", 8))),
            "seed": int(spec.get("seed", 0)),
            "generation": 0,
            "spec": self._validate_spec(spec),
        }
        if exp["generations"] < 1 or exp["population"] < 1:
            raise ExperimentError(
                "generations and population must be >= 1")
        self._make_policy(exp)      # reject bad policy/config at submit
        self._store.commit_manifest(exp)
        with self._lock:
            self._exps[exp["id"]] = exp
            self._trials[exp["id"]] = {}
            self._counts["submitted"] += 1
            running = sum(1 for e in self._exps.values()
                          if e["state"] == "running")
        self._m_submitted.inc()
        self._g_running.set(running)
        self._spawn(exp["id"])
        return self.status(exp["id"])

    @staticmethod
    def _validate_spec(spec: dict) -> dict:
        clean = {}
        prompts = spec.get("eval_prompts")
        if prompts is not None:
            if not isinstance(prompts, list) or not prompts or not all(
                    isinstance(p, (list, tuple)) and p for p in prompts):
                raise ExperimentError(
                    "eval_prompts must be a non-empty list of non-empty "
                    "token-id lists")
            clean["eval_prompts"] = [[int(t) for t in p]
                                     for p in prompts]
        for k, cast in (("eval_steps", int), ("eval_seed", int)):
            if spec.get(k) is not None:
                clean[k] = cast(spec[k])
        if spec.get("promote") is not None:
            clean["promote"] = bool(spec["promote"])
        return clean

    def _make_policy(self, exp: dict) -> SearchPolicy:
        if self.policy_factory is not None:
            return self.policy_factory(exp, self.config)
        cls = POLICIES.get(exp["policy"])
        if cls is None:
            raise ExperimentError(
                f"unknown policy {exp['policy']!r}; have "
                f"{sorted(POLICIES)}")
        if self.config is None and exp["policy"] != "ensemble":
            raise ExperimentError(
                f"policy {exp['policy']!r} needs a base config with "
                "Range tuneables attached to the manager")
        return cls(self.config, population=exp["population"],
                   generations=exp["generations"], seed=exp["seed"])

    def _get(self, exp_id: str) -> dict:
        with self._lock:
            exp = self._exps.get(exp_id)
        if exp is None:
            raise KeyError(f"no such experiment: {exp_id}")
        return exp

    def status(self, exp_id: str) -> dict:
        exp = self._get(exp_id)
        with self._lock:
            trials = self._trials.get(exp_id, {})
            by_status: Dict[str, int] = {}
            for t in trials.values():
                by_status[t["status"]] = by_status.get(t["status"], 0) + 1
            doc = {
                "id": exp["id"], "name": exp["name"],
                "state": exp["state"], "created": exp["created"],
                "policy": exp["policy"],
                "generations": exp["generations"],
                "population": exp["population"],
                "generation": exp.get("generation", 0),
                "trials": {"total": len(trials), **by_status},
            }
            for k in ("baseline_score", "best", "promotion", "error"):
                if exp.get(k) is not None:
                    doc[k] = exp[k]
        return doc

    def list_experiments(self) -> dict:
        with self._lock:
            ids = sorted(self._exps,
                         key=lambda e: self._exps[e]["created"])
        return {"experiments": [self.status(e) for e in ids]}

    def summary(self) -> dict:
        """The fleet-level view ``/fleet.json`` merges: experiment
        counts by state plus trial progress."""
        with self._lock:
            states: Dict[str, int] = {}
            for e in self._exps.values():
                states[e["state"]] = states.get(e["state"], 0) + 1
            return {
                "total": len(self._exps),
                "by_state": states,
                "trials": sum(len(t) for t in self._trials.values()),
                "trials_inflight": len(self._claimed),
                **{k: v for k, v in self._counts.items()},
            }

    def cancel(self, exp_id: str) -> dict:
        """Cancel: mark terminal, stop scheduling new trials, sweep the
        claim ledger.  The trial currently inside ``Trainer.run`` (if
        any) finishes and commits — completed work is never thrown away
        — and the drive thread exits at its next liveness check."""
        exp = self._get(exp_id)
        with self._lock:
            already = exp["state"] in _TERMINAL
            if not already:
                exp["state"] = "cancelled"
                self._cancelled.add(exp_id)
                self._counts["cancelled"] += 1
                running = sum(1 for e in self._exps.values()
                              if e["state"] == "running")
            stale = [k for k in self._claimed if k[0] == exp_id]
            man = dict(exp)
        if not already:
            for key in stale:
                self._abort_trial(key)
            self._store.commit_manifest(man)
            self._g_running.set(running)
        return self.status(exp_id)

    def wait(self, exp_id: str, timeout_s: float = 120.0) -> bool:
        """Block until the experiment is terminal (poll-based:
        terminality is a disk-backed property)."""
        deadline = time.monotonic() + float(timeout_s)
        while time.monotonic() < deadline:
            with self._lock:
                exp = self._exps.get(exp_id)
                if exp is not None and exp["state"] in _TERMINAL:
                    return True
            time.sleep(0.05)
        return False

    # -- drive loop (one thread per experiment) ------------------------------
    def _check_live(self, exp_id: str) -> None:
        with self._lock:
            dead = (self._stop_evt.is_set()
                    or exp_id in self._cancelled)
        if dead:
            raise _Cancelled(exp_id)

    def _run_experiment(self, exp_id: str):
        try:
            self._drive(exp_id)
        except _Cancelled:
            pass        # cancel() committed the state; stop() leaves
            # "running" on disk for the successor's resume
        except faults.FaultInjected as e:
            # simulated process death (trial_crash_at_step): state
            # stays "running" on disk, a fresh manager must resume —
            # deliberately NOT recorded as a failed experiment
            self.warning("experiment %s crashed (injected): %s",
                         exp_id, e)
        except Exception as e:  # noqa: BLE001 — a failed experiment
            # must not kill the manager; record and move on
            self.exception("experiment %s failed", exp_id)
            with self._lock:
                exp = self._exps[exp_id]
                exp["state"] = "failed"
                exp["error"] = str(e)[:500]
                self._counts["failed"] += 1
                running = sum(1 for e2 in self._exps.values()
                              if e2["state"] == "running")
                man = dict(exp)
            self._store.commit_manifest(man)
            self._g_running.set(running)
        finally:
            with self._lock:
                self._threads.pop(exp_id, None)

    def _drive(self, exp_id: str):
        with self._lock:
            exp = dict(self._exps[exp_id])
        policy = self._make_policy(exp)
        memo: Dict[str, Tuple[int, int]] = {}
        for gen in range(policy.n_generations):
            self._check_live(exp_id)
            genomes = policy.propose(gen)
            self._train_generation(exp, gen, genomes, policy, memo)
            scores = self._score_generation(exp, gen, genomes)
            policy.observe(gen, scores)
            with self._lock:
                live = self._exps[exp_id]
                live["generation"] = gen + 1
                if gen == 0:
                    live["baseline_score"] = scores[0]
                man = dict(live)
            self._store.commit_manifest(man)
            self.info("experiment %s gen %d/%d: best=%.6g", exp_id,
                      gen + 1, policy.n_generations, min(scores))
        self._promote(exp_id)

    def _trial(self, exp_id: str, gen: int, idx: int) -> Optional[dict]:
        with self._lock:
            return self._trials.get(exp_id, {}).get((gen, idx))

    @staticmethod
    def _trial_seed(exp: dict, gen: int, idx: int) -> int:
        # pure function of (experiment seed, generation, index): an
        # interrupted trial restarts with the identical seed
        return int(exp["seed"]) + gen * 100003 + idx

    @staticmethod
    def _note_memo(memo: Dict[str, Tuple[int, int]], genome: dict,
                   t: dict) -> None:
        src = t.get("cached_from") or (t["generation"], t["index"])
        memo.setdefault(_genome_key(genome), (int(src[0]), int(src[1])))

    def _train_generation(self, exp: dict, gen: int,
                          genomes: List[dict], policy: SearchPolicy,
                          memo: Dict[str, Tuple[int, int]]) -> None:
        exp_id = exp["id"]
        todo: List[Tuple[int, dict]] = []
        for idx, genome in enumerate(genomes):
            self._check_live(exp_id)
            t = self._trial(exp_id, gen, idx)
            if t is not None:
                # resume: the committed trial must match the replay
                if t.get("genome") != genome:
                    raise ExperimentError(
                        f"experiment {exp_id} trial g{gen}t{idx} on "
                        "disk does not match its deterministic replay "
                        f"(seed {exp['seed']}): the store and the spec "
                        "come from different histories")
                self._note_memo(memo, genome, t)
                continue
            src = memo.get(_genome_key(genome))
            if policy.dedup and src is not None:
                self._cache_trial(exp, gen, idx, genome, src)
                self._note_memo(memo, genome,
                                self._trial(exp_id, gen, idx))
                continue
            todo.append((idx, genome))
        if not todo:
            return
        if self.workers > 1 and self.cli_argv is not None:
            self._train_subprocess(exp, gen, todo, memo)
            return
        for idx, genome in todo:
            self._check_live(exp_id)
            self._train_trial(exp, gen, idx, genome, policy)
            self._note_memo(memo, genome, self._trial(exp_id, gen, idx))

    def _cache_trial(self, exp: dict, gen: int, idx: int, genome: dict,
                     src: Tuple[int, int]) -> None:
        """A genome already materialized earlier (a GA elite carried
        over): commit a ``cached`` trial pointing at the source instead
        of retraining — same claim/commit ledger discipline as a real
        training."""
        exp_id = exp["id"]
        key = (exp_id, gen, idx)
        src_doc = self._trial(exp_id, *src) or {}
        doc = {"generation": gen, "index": idx,
               "seed": self._trial_seed(exp, gen, idx),
               "genome": dict(genome), "status": "cached",
               "cached_from": [int(src[0]), int(src[1])],
               "snapshot": src_doc.get("snapshot"),
               "best_value": src_doc.get("best_value")}
        if src_doc.get("score") is not None:
            doc["score"] = src_doc["score"]
        self._claim_trial(key)
        committed = False
        try:
            self._commit_trial(key, doc)
            committed = True
            self._m_trials_cached.inc()
        finally:
            if not committed:
                self._abort_trial(key)

    def _maybe_crash_trial(self) -> None:
        """The ``trial_crash_at_step`` injection point: the manager's
        Nth trial launch (process-lifetime ordinal) dies after claiming
        its ledger entry and before any commit — a simulated process
        death the resume path must absorb."""
        with self._lock:
            self._trial_launches += 1
            n = self._trial_launches
        if not faults.enabled():
            return
        plan = faults.get_plan()
        if plan.trial_crash_at_step \
                and n == plan.trial_crash_at_step \
                and faults.fire_once("trial_crash",
                                     plan.trial_crash_at_step):
            raise faults.FaultInjected(
                f"trial_crash_at_step: killing trial launch {n}")

    def _train_trial(self, exp: dict, gen: int, idx: int, genome: dict,
                     policy: SearchPolicy) -> None:
        exp_id = exp["id"]
        key = (exp_id, gen, idx)
        seed = self._trial_seed(exp, gen, idx)
        self._claim_trial(key)
        committed = False
        try:
            self._maybe_crash_trial()
            doc = {"generation": gen, "index": idx, "seed": seed,
                   "genome": dict(genome)}
            try:
                cfg = policy.materialize(genome)
                trial = {"experiment": exp_id, "generation": gen,
                         "index": idx, "seed": seed,
                         "genome": dict(genome),
                         "out_dir": self._store.snap_dir(
                             exp_id, gen, idx)}
                trainer = self.trial_factory(trial, cfg)
                trainer.initialize(seed=seed)
                trainer.run()
                snap = Snapshotter(f"g{gen}t{idx}", trial["out_dir"],
                                   interval=1)
                path = snap.save("final", trainer._payload())
                doc.update(status="trained", snapshot=path,
                           best_value=float(
                               trainer.decision.best_value))
            except faults.FaultInjected:
                raise           # a simulated crash, not a failed trial
            except Exception as e:  # noqa: BLE001 — one broken config
                # (materialize/train blowing up) is a failed TRIAL, not
                # a failed experiment: record it, score it inf, go on
                self.warning("trial %s g%dt%d failed: %s", exp_id, gen,
                             idx, e)
                doc.update(status="failed", snapshot=None,
                           best_value=None, error=str(e)[:500])
            self._commit_trial(key, doc)
            committed = True
            self._m_trials.inc()
        finally:
            if not committed:
                self._abort_trial(key)

    def _train_subprocess(self, exp: dict, gen: int,
                          todo: List[Tuple[int, dict]],
                          memo: Dict[str, Tuple[int, int]]) -> None:
        """Bounded parallel trials: each todo genome becomes one
        standalone CLI training (inline ``path=value`` overrides +
        derived seed + per-trial snapshot dir) on a ``workers``-wide
        subprocess pool.  All trials claim before the pool runs and
        commit/abort after — a crash mid-pool leaves only claims the
        exit sweeps drop, and committed snapshots resume as usual."""
        from ..parallel.pool import CliRunner
        exp_id = exp["id"]
        keys: List[Tuple[Tuple[str, int, int], int, dict]] = []
        jobs: List[List[str]] = []
        try:
            for idx, genome in todo:
                self._check_live(exp_id)
                key = (exp_id, gen, idx)
                self._claim_trial(key)
                keys.append((key, idx, genome))
                self._maybe_crash_trial()
                out_dir = self._store.snap_dir(exp_id, gen, idx)
                ovs = [f"{p}={json.dumps(v)}"
                       for p, v in genome.items()]
                jobs.append([
                    *self.cli_argv, *ovs,
                    "--random-seed",
                    str(self._trial_seed(exp, gen, idx)),
                    "--snapshot-dir", out_dir,
                ])
            runner = CliRunner(n_workers=self.workers, env=self.env)
            results = runner.run_jobs(jobs)
        except BaseException:
            for key, _idx, _genome in keys:
                self._abort_trial(key)
            raise
        for (key, idx, genome), res in zip(keys, results):
            doc = {"generation": gen, "index": idx,
                   "seed": self._trial_seed(exp, gen, idx),
                   "genome": dict(genome)}
            snap = self._find_snapshot(
                self._store.snap_dir(exp_id, gen, idx))
            if "error" in res or res.get("best_value") is None \
                    or snap is None:
                doc.update(status="failed", snapshot=snap,
                           best_value=None,
                           error=str(res.get(
                               "error", "no best_value/snapshot"))[:500])
            else:
                doc.update(status="trained", snapshot=snap,
                           best_value=float(res["best_value"]))
            self._commit_trial(key, doc)
            self._m_trials.inc()
            self._note_memo(memo, genome, doc)

    @staticmethod
    def _find_snapshot(out_dir: str) -> Optional[str]:
        """Resolve a CLI trial's final snapshot via the snapshotter's
        ``_best``/``_current`` links (the EnsembleTrainer farm-out
        idiom)."""
        import os
        if not os.path.isdir(out_dir):
            return None
        for link in ("_best.json", "_current.json"):
            cands = [f for f in os.listdir(out_dir)
                     if f.endswith(link)]
            if cands:
                return os.path.realpath(
                    os.path.join(out_dir, cands[0]))
        return None

    # -- scoring -------------------------------------------------------------
    def _score_generation(self, exp: dict, gen: int,
                          genomes: List[dict]) -> List[float]:
        exp_id = exp["id"]
        spec = exp["spec"]
        sweep = []
        for idx in range(len(genomes)):
            t = self._trial(exp_id, gen, idx)
            if t is None:
                raise ExperimentError(
                    f"experiment {exp_id} trial g{gen}t{idx} missing "
                    "after the training phase")
            if t["status"] == "trained" and t.get("score") is None:
                sweep.append(t)
        if sweep:
            self._check_live(exp_id)
            prompts = spec.get("eval_prompts") or self.eval_prompts
            if self.jobs is not None and prompts:
                cands = [{"name": f"g{gen}t{t['index']}",
                          "prompts": prompts, "trial": t}
                         for t in sweep]
                results = score_candidates(
                    self.jobs, cands, self.scorer,
                    steps=int(spec.get(
                        "eval_steps",
                        root.common.experiment.get("eval_steps", 8))),
                    seed=int(spec.get("eval_seed", 0)),
                    timeout_s=self.eval_timeout_s)
                for t, r in zip(sweep, results):
                    self._recommit(exp_id, dict(
                        t, status="scored", score=float(r["score"]),
                        job_id=r["job_id"]))
            else:
                # no batch lane attached: the training metric IS the
                # score (still deterministic, still resumable)
                for t in sweep:
                    bv = t.get("best_value")
                    self._recommit(exp_id, dict(
                        t, status="scored",
                        score=float(bv) if bv is not None
                        else math.inf))
        scores = []
        for idx in range(len(genomes)):
            scores.append(self._resolved_score(
                exp_id, self._trial(exp_id, gen, idx)))
        return scores

    def _recommit(self, exp_id: str, doc: dict) -> None:
        """Update an already-committed trial (score attach): a plain
        durable re-commit, no ledger traffic — the trial's claim was
        released when its training committed."""
        self._store.commit_trial(exp_id, doc)
        with self._lock:
            self._trials.setdefault(exp_id, {})[
                (doc["generation"], doc["index"])] = doc

    def _resolved_score(self, exp_id: str, t: dict) -> float:
        if t["status"] == "failed":
            return math.inf
        if t["status"] == "cached":
            if t.get("score") is not None:
                return float(t["score"])
            src = self._trial(exp_id, *t["cached_from"])
            score = float(src["score"])
            self._recommit(exp_id, dict(t, score=score))
            return score
        return float(t["score"])

    # -- promotion -----------------------------------------------------------
    def _promote(self, exp_id: str) -> None:
        """The gate + the swap.  Winner = lowest resolved score across
        every trial.  It ships only when (a) a promotion hook is
        attached and the spec did not disable it, (b) it is not the
        baseline trial ``(0, 0)`` itself, and (c) it beats the baseline
        score by more than ``experiment.promote_margin``.  The swap's
        own two-phase atomicity guarantees a failed promotion leaves
        the old version serving everywhere."""
        with self._lock:
            exp = self._exps[exp_id]
            spec = exp["spec"]
            trials = dict(self._trials.get(exp_id, {}))
        scored = {k: t for k, t in trials.items()
                  if t.get("score") is not None}
        promotion: dict
        best_doc = None
        if not scored:
            promotion = {"promoted": False, "reason": "no scored trials"}
        else:
            best_k = min(scored,
                         key=lambda k: (scored[k]["score"], k))
            best = scored[best_k]
            best_doc = {"generation": best_k[0], "index": best_k[1],
                        "score": best["score"],
                        "snapshot": best.get("snapshot"),
                        "genome": best.get("genome")}
            self._g_best.set(float(best["score"]))
            baseline = scored.get((0, 0))
            base_score = baseline["score"] if baseline else None
            want = spec.get("promote", True) \
                and self.promote_fn is not None
            if not want:
                promotion = {"promoted": False,
                             "reason": "promotion disabled (no hook "
                                       "attached or spec promote=false)"}
            elif best_k == (0, 0):
                promotion = {"promoted": False,
                             "reason": "baseline is already the best "
                                       "candidate"}
            elif base_score is not None and not (
                    best["score"] < base_score - self.promote_margin):
                promotion = {
                    "promoted": False,
                    "reason": f"improvement {base_score - best['score']:.6g}"
                              f" below promote_margin "
                              f"{self.promote_margin:.6g}"}
            elif not best.get("snapshot"):
                promotion = {"promoted": False,
                             "reason": "winner has no snapshot"}
            else:
                promotion = self._run_swap(best)
        with self._lock:
            exp = self._exps[exp_id]
            exp["state"] = "done"
            exp["best"] = best_doc
            exp["promotion"] = promotion
            self._counts["completed"] += 1
            running = sum(1 for e in self._exps.values()
                          if e["state"] == "running")
            man = dict(exp)
        self._store.commit_manifest(man)
        self._m_completed.inc()
        self._g_running.set(running)
        self.info("experiment %s done: best=%s promotion=%s", exp_id,
                  best_doc and best_doc["score"], promotion["reason"]
                  if "reason" in promotion else promotion)

    def _run_swap(self, best: dict) -> dict:
        try:
            out = self.promote_fn(best["snapshot"])
        except Exception as e:  # noqa: BLE001 — a promotion hook
            # blowing up must leave a failed-promotion record, never a
            # failed experiment (the fleet is still serving the old
            # version; the swap never started or rolled back)
            out = {"swapped": False, "error": str(e)[:500]}
        if isinstance(out, dict):
            swapped = bool(out.get("swapped"))
            detail = {k: out[k] for k in
                      ("phase", "rolled_back", "error") if k in out}
            if out.get("errors"):
                detail["errors"] = {str(k): str(v)[:200]
                                    for k, v in out["errors"].items()}
        else:
            swapped = bool(out)
            detail = {}
        if swapped:
            self._m_promotions.inc()
            return {"promoted": True, "reason": "swapped",
                    "snapshot": best["snapshot"], **detail}
        self._m_promote_failures.inc()
        return {"promoted": False,
                "reason": "swap failed (rolled back; old version keeps "
                          "serving)",
                "snapshot": best["snapshot"], **detail}


def handle_experiments_request(manager: Optional[ExperimentManager],
                               method: str, path: str,
                               body: Optional[dict]
                               ) -> Optional[Tuple[int, object]]:
    """Shared REST glue for the experiment API — both the fleet server
    and a single replica route ``/experiments*`` requests here.
    Returns ``(status, doc)`` or None when ``path`` is not an
    experiments route (the caller falls through to its own 404)."""
    from urllib.parse import urlparse
    parsed = urlparse(path)
    parts = [p for p in parsed.path.split("/") if p]
    if not parts or parts[0] != "experiments":
        return None
    if manager is None:
        return 404, {"error": "no experiment manager attached (set "
                              "experiment.dir and wire an "
                              "ExperimentManager; see "
                              "docs/experiments.md)"}
    try:
        if method == "POST" and len(parts) == 1:
            return 200, manager.submit(body or {})
        if method == "GET" and len(parts) == 1:
            return 200, manager.list_experiments()
        if method == "GET" and len(parts) == 2:
            return 200, manager.status(parts[1])
        if method == "DELETE" and len(parts) == 2:
            return 200, manager.cancel(parts[1])
    except KeyError as e:
        return 404, {"error": str(e)}
    except (ExperimentError, TypeError, ValueError) as e:
        return 400, {"error": str(e)}
    return 404, {"error": f"unknown experiments route {parsed.path}"}
