"""Durable experiment persistence (the ``runtime/jobs.py`` store idiom).

One directory per experiment under ``base``::

    <base>/<exp_id>/manifest.json            # spec + coarse state
    <base>/<exp_id>/trials/g0001_t0003.json  # one file per finished trial
    <base>/<exp_id>/snaps/g0001_t0003/       # that trial's snapshots

The manifest is the experiment's coarse record (spec, state, promotion
outcome) and is re-committed on every state change; the per-trial files
are the fine-grained progress record — a trial exists on disk exactly
when its training (or cache-copy) finished, so a restarted manager
recomputes "what is left to run" from the trial files alone, never from
counters a crash could have torn.  Every write stages through the
snapshotter's tmp-fsync-rename helpers (``_commit_bytes``; the VR704
lint rule pins the idiom here too): a crash leaves the previous
committed state, never a half-written file a resume would trust.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional, Tuple

from ..runtime.snapshotter import _commit_bytes, _fsync_dir


class ExperimentStore:
    """Filesystem layout + committed reads/writes for experiments."""

    def __init__(self, base: str):
        self.base = str(base)
        os.makedirs(self.base, exist_ok=True)

    def exp_dir(self, exp_id: str) -> str:
        return os.path.join(self.base, exp_id)

    def snap_dir(self, exp_id: str, gen: int, idx: int) -> str:
        return os.path.join(self.exp_dir(exp_id), "snaps",
                            f"g{int(gen):04d}_t{int(idx):04d}")

    def _trial_path(self, exp_id: str, gen: int, idx: int) -> str:
        return os.path.join(self.exp_dir(exp_id), "trials",
                            f"g{int(gen):04d}_t{int(idx):04d}.json")

    def commit_manifest(self, doc: dict) -> None:
        d = self.exp_dir(doc["id"])
        os.makedirs(os.path.join(d, "trials"), exist_ok=True)
        _commit_bytes(os.path.join(d, "manifest.json"),
                      json.dumps(doc).encode())
        _fsync_dir(d)

    def read_manifest(self, exp_id: str) -> Optional[dict]:
        try:
            with open(os.path.join(self.exp_dir(exp_id),
                                   "manifest.json")) as f:
                return json.load(f)
        except (OSError, json.JSONDecodeError):
            return None

    def commit_trial(self, exp_id: str, doc: dict) -> None:
        path = self._trial_path(exp_id, doc["generation"], doc["index"])
        _commit_bytes(path, json.dumps(doc).encode())
        _fsync_dir(os.path.dirname(path))

    def has_trial(self, exp_id: str, gen: int, idx: int) -> bool:
        return os.path.exists(self._trial_path(exp_id, gen, idx))

    def read_trial(self, exp_id: str, gen: int, idx: int
                   ) -> Optional[dict]:
        try:
            with open(self._trial_path(exp_id, gen, idx)) as f:
                return json.load(f)
        except (OSError, json.JSONDecodeError):
            return None

    def load_trials(self, exp_id: str) -> Dict[Tuple[int, int], dict]:
        """Every committed trial of one experiment, keyed by
        ``(generation, index)`` — the on-disk trial files ARE the
        progress record the resume path trusts."""
        out: Dict[Tuple[int, int], dict] = {}
        tdir = os.path.join(self.exp_dir(exp_id), "trials")
        try:
            names = sorted(os.listdir(tdir))
        except OSError:
            return out
        for name in names:
            try:
                with open(os.path.join(tdir, name)) as f:
                    doc = json.load(f)
            except (OSError, json.JSONDecodeError):
                continue        # torn tmp leftovers never commit
            out[(int(doc["generation"]), int(doc["index"]))] = doc
        return out

    def load_all(self) -> List[dict]:
        """Every persisted experiment manifest, oldest first.  Dirs
        without a readable manifest are half-created (crash before the
        first commit) and are skipped — the client never got a 200 for
        them."""
        docs: List[dict] = []
        try:
            entries = sorted(os.listdir(self.base))
        except OSError:
            return docs
        for name in entries:
            doc = self.read_manifest(name)
            if doc is not None:
                docs.append(doc)
        docs.sort(key=lambda d: d.get("created", 0.0))
        return docs
