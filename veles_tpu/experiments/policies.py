"""Search policies: who decides which trial configs an experiment runs.

A policy is a *stepwise* generator of trial genomes (``{config path ->
value}`` over the base config's ``Range`` tuneables) that the experiment
manager drives one generation at a time::

    genomes = policy.propose(g)          # deterministic from (seed, g)
    ...train + score each genome...      # manager's job
    policy.observe(g, scores)            # lower score = better
    cfg = policy.materialize(genome)     # genome -> full Config

The split matters for crash safety: the manager persists trial *scores*,
not populations — on resume it re-proposes every generation from scratch
and replays the recorded scores through ``observe``, so ``propose(g)``
MUST be a pure function of ``(seed, g)`` plus everything observed before
``g``.  :meth:`~veles_tpu.genetics.GeneticOptimizer.generation_rng` is
exactly that contract for the GA.

Two invariants every policy keeps:

* ``propose(0)[0]`` is the **baseline** genome — the base config's
  current values.  Trial ``(0, 0)``'s score is the promotion gate's
  reference point: a winner only ships if it beats what is already
  serving by the configured margin.
* genomes are JSON-serializable (they are committed into trial files).

``dedup`` (class attribute, default True) lets the manager collapse
repeated genomes — a GA elite re-proposed in the next generation is the
*same* candidate and must not retrain (it becomes a ``cached`` trial).
:class:`EnsemblePolicy` turns it off: its trials share one genome on
purpose and differ only by trial seed.
"""

from __future__ import annotations

import itertools
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..config import Config
from ..genetics import GeneticOptimizer


def _policy_driven(cfg) -> float:
    raise RuntimeError(
        "policy-driven GA: fitness comes from ExperimentManager scores "
        "via observe(), never from an in-loop fitness_fn")


class SearchPolicy:
    """Base contract (see the module docstring for the drive cycle)."""

    #: may the manager collapse equal genomes into cached trials?
    dedup = True
    #: generations this policy wants; the manager drives 0..n-1.
    n_generations = 1

    def propose(self, generation: int) -> List[Dict[str, object]]:
        raise NotImplementedError

    def observe(self, generation: int,
                scores: Sequence[float]) -> None:
        raise NotImplementedError

    def materialize(self, genome: Dict[str, object]) -> Config:
        raise NotImplementedError


class GeneticPolicy(SearchPolicy):
    """The flagship: the rebuilt :class:`~veles_tpu.genetics.
    GeneticOptimizer` over config ``Range`` tuneables, driven stepwise.
    Generation 0 is the seed individual (baseline) plus randoms from
    ``generation_rng(0)``; generation g breeds from the observed
    generation g-1 with ``generation_rng(g)`` — so any generation
    replays bitwise from ``(seed, g)`` and the stored scores."""

    def __init__(self, config: Config, *, population: int = 8,
                 generations: int = 4, seed: int = 0, **ga_kw):
        self.ga = GeneticOptimizer(
            config, fitness_fn=_policy_driven,
            population_size=int(population),
            generations=int(generations), seed=int(seed), **ga_kw)
        self.population = int(population)
        self.n_generations = int(generations)
        self._pop = None
        self._gen = -1

    def propose(self, generation: int) -> List[Dict[str, object]]:
        generation = int(generation)
        if generation == 0:
            g0 = self.ga.generation_rng(0)
            pop = [self.ga.seed_individual()] + [
                self.ga.random_individual(g0)
                for _ in range(self.population - 1)]
        else:
            if self._gen != generation - 1 or self._pop is None \
                    or not all(i.evaluated for i in self._pop):
                raise ValueError(
                    f"propose({generation}) needs generation "
                    f"{generation - 1} proposed and observed first")
            pop = self.ga.breed(self._pop,
                                self.ga.generation_rng(generation))
        self._pop = pop
        self._gen = generation
        return [dict(i.genome) for i in pop]

    def observe(self, generation: int,
                scores: Sequence[float]) -> None:
        if int(generation) != self._gen or self._pop is None \
                or len(scores) != len(self._pop):
            raise ValueError(
                f"observe({generation}) does not match the last "
                f"proposed generation {self._gen}")
        for ind, s in zip(self._pop, scores):
            ind.fitness = float(s)
            ind.evaluated = True

    def materialize(self, genome: Dict[str, object]) -> Config:
        return self.ga.materialize(genome)


class RandomPolicy(SearchPolicy):
    """Random-search baseline: every generation is an independent draw
    from ``generation_rng(g)`` (scores are ignored) — the control arm a
    GA claim is measured against."""

    def __init__(self, config: Config, *, population: int = 8,
                 generations: int = 4, seed: int = 0):
        self.ga = GeneticOptimizer(
            config, fitness_fn=_policy_driven,
            population_size=int(population),
            generations=int(generations), seed=int(seed))
        self.population = int(population)
        self.n_generations = int(generations)

    def propose(self, generation: int) -> List[Dict[str, object]]:
        rng = self.ga.generation_rng(int(generation))
        out: List[Dict[str, object]] = []
        if int(generation) == 0:
            out.append(dict(self.ga.seed_individual().genome))
        while len(out) < self.population:
            out.append(dict(self.ga.random_individual(rng).genome))
        return out

    def observe(self, generation: int,
                scores: Sequence[float]) -> None:
        pass                    # memoryless by design

    def materialize(self, genome: Dict[str, object]) -> Config:
        return self.ga.materialize(genome)


class GridPolicy(SearchPolicy):
    """Full-factorial grid baseline: each tuneable gets evenly spaced
    levels (or its discrete choices), the cartesian product is chunked
    into generations of ``population`` trials, wrapping around if the
    grid is smaller than the trial budget (the manager's dedup turns
    wrapped repeats into cached trials).  Purely deterministic; scores
    are ignored."""

    def __init__(self, config: Config, *, population: int = 8,
                 generations: int = 4, seed: int = 0):
        self.ga = GeneticOptimizer(
            config, fitness_fn=_policy_driven,
            population_size=int(population),
            generations=int(generations), seed=int(seed))
        self.population = int(population)
        self.n_generations = int(generations)
        slots = max(self.population * self.n_generations - 1, 1)
        axes: List[List[object]] = []
        n_axes = len(self.ga.tuneables)
        per_axis = max(2, int(round(slots ** (1.0 / n_axes))))
        for p, r in self.ga.tuneables.items():
            if r.choices is not None:
                axes.append(list(r.choices))
                continue
            lo, hi = self.ga._gene_bounds(p)
            levels = np.linspace(lo, hi, per_axis)
            axes.append([int(round(v)) if r.integer else float(v)
                         for v in levels])
        paths = list(self.ga.tuneables)
        self._points = [dict(zip(paths, combo))
                        for combo in itertools.product(*axes)]

    def propose(self, generation: int) -> List[Dict[str, object]]:
        generation = int(generation)
        out: List[Dict[str, object]] = []
        if generation == 0:
            out.append(dict(self.ga.seed_individual().genome))
        base = max(generation * self.population - 1, 0)
        k = base
        while len(out) < self.population:
            out.append(dict(self._points[k % len(self._points)]))
            k += 1
        return out

    def observe(self, generation: int,
                scores: Sequence[float]) -> None:
        pass                    # exhaustive by design

    def materialize(self, genome: Dict[str, object]) -> Config:
        return self.ga.materialize(genome)


class EnsemblePolicy(SearchPolicy):
    """:class:`~veles_tpu.ensemble.EnsembleTrainer` as a one-generation
    degenerate case: N trials of the *same* config whose only variation
    is the trial seed (the manager derives per-trial seeds from the
    experiment seed, like the ensemble's ``base_seed + member``), so the
    trial factory can split data / init weights per member.  ``dedup``
    is off — the shared empty genome is intentional, every member must
    train.  The "winner" is simply the best member; with the promotion
    gate this doubles as seed-selection for the serving fleet."""

    dedup = False

    def __init__(self, config: Optional[Config] = None, *,
                 population: int = 8, generations: int = 1,
                 seed: int = 0):
        self.config = config
        self.population = int(population)
        self.n_generations = 1  # degenerate by definition

    def propose(self, generation: int) -> List[Dict[str, object]]:
        return [{} for _ in range(self.population)]

    def observe(self, generation: int,
                scores: Sequence[float]) -> None:
        pass

    def materialize(self, genome: Dict[str, object]) -> Config:
        cfg = Config()
        if self.config is not None:
            cfg.update(self.config.to_dict(unwrap_ranges=True))
        return cfg


#: name -> class, the REST spec's ``"policy"`` field.
POLICIES = {
    "genetic": GeneticPolicy,
    "random": RandomPolicy,
    "grid": GridPolicy,
    "ensemble": EnsemblePolicy,
}
