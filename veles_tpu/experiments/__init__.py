"""Fleet-scale experiment manager: train → select → hot-swap with no
human in the loop (docs/experiments.md)."""

from .manager import (ExperimentError, ExperimentManager,
                      default_scorer, fleet_promoter,
                      handle_experiments_request)
from .policies import (POLICIES, EnsemblePolicy, GeneticPolicy,
                       GridPolicy, RandomPolicy, SearchPolicy)
from .store import ExperimentStore
