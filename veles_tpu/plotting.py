"""Plotting / metric visualization.

Reference parity: the L10 plotting stack (reference: veles/plotter.py:48
Plotter base; veles/plotting_units.py — AccumulatingPlotter :52,
MatrixPlotter confusion :184, Histogram :536; served over a ZMQ PUB socket
to a separate matplotlib GraphicsClient process,
veles/graphics_server.py:65).

TPU redesign: no socket, no second process — a MetricsRecorder accumulates
series on the host (metrics are tiny scalars), renders (a) ASCII sparklines
for the terminal, (b) PNG via matplotlib-Agg when available, (c) JSONL for
external dashboards. The reference's "plotters are units inside the graph"
becomes "recorders subscribe to Trainer epochs" — plotting must never sync
the device pipeline."""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional, Sequence

import numpy as np

from .logger import Logger

_SPARK = "▁▂▃▄▅▆▇█"


def sparkline(values: Sequence[float], width: int = 40) -> str:
    """ASCII sparkline of a series (terminal plotting path)."""
    if not values:
        return ""
    v = np.asarray(values, np.float64)
    if len(v) > width:
        # re-bin to width
        edges = np.linspace(0, len(v), width + 1).astype(int)
        v = np.array([v[a:b].mean() if b > a else v[min(a, len(v) - 1)]
                      for a, b in zip(edges[:-1], edges[1:])])
    lo, hi = float(np.nanmin(v)), float(np.nanmax(v))
    span = (hi - lo) or 1.0
    idx = ((v - lo) / span * (len(_SPARK) - 1)).astype(int)
    return "".join(_SPARK[i] for i in idx)


class MetricsRecorder(Logger):
    """Accumulating series recorder (reference: AccumulatingPlotter)."""

    def __init__(self, name: str = "metrics", out_dir: Optional[str] = None,
                 graphics=None, autosave_png: bool = False):
        self.name = name
        self.out_dir = out_dir
        self.series: Dict[str, List[float]] = {}
        # Optional live channel (graphics.GraphicsServer): every record()
        # is also broadcast to subscribed renderer processes (reference:
        # plotters pickled onto the ZMQ PUB socket, veles/plotter.py:147).
        self.graphics = graphics
        # Refresh the PNG on every record() — the browser status page
        # embeds it for live watching (runtime/status.py). Epoch cadence,
        # host-side only; never syncs the device pipeline.
        self.autosave_png = autosave_png
        self._jsonl = None
        if out_dir:
            os.makedirs(out_dir, exist_ok=True)
            self._jsonl = open(os.path.join(out_dir, name + ".jsonl"), "a")

    def record(self, step: int, **values: float) -> None:
        rec = {"step": step}
        for k, v in values.items():
            try:
                v = float(v)
            except (TypeError, ValueError):
                continue
            self.series.setdefault(k, []).append(v)
            rec[k] = v
        if self._jsonl is not None:
            self._jsonl.write(json.dumps(rec) + "\n")
            self._jsonl.flush()
        if self.graphics is not None:
            self.graphics.publish(
                {"kind": "metrics", "step": step,
                 "values": {k: v for k, v in rec.items() if k != "step"}})
        if self.autosave_png and self.out_dir:
            self.save_png()

    def summary(self, width: int = 40) -> str:
        """Terminal rendering of all series."""
        lines = []
        for k, v in sorted(self.series.items()):
            lines.append(f"{k:>24s} {sparkline(v, width)}  "
                         f"last={v[-1]:.4g} best={min(v):.4g}")
        return "\n".join(lines)

    def save_png(self, path: Optional[str] = None) -> Optional[str]:
        """Render all series with matplotlib-Agg when available
        (reference: the GraphicsClient matplotlib backends)."""
        try:
            import matplotlib
            matplotlib.use("Agg")
            import matplotlib.pyplot as plt
        except ImportError:
            self.warning("matplotlib unavailable; skipping PNG")
            return None
        path = path or os.path.join(self.out_dir or ".",
                                    self.name + ".png")
        n = max(len(self.series), 1)
        fig, axes = plt.subplots(n, 1, figsize=(8, 2.2 * n), squeeze=False)
        for ax, (k, v) in zip(axes[:, 0], sorted(self.series.items())):
            ax.plot(v)
            ax.set_title(k, fontsize=9)
            ax.grid(True, alpha=0.3)
        fig.tight_layout()
        fig.savefig(path, dpi=100)
        plt.close(fig)
        return path

    def close(self):
        if self._jsonl is not None:
            self._jsonl.close()
            self._jsonl = None


def confusion_matrix(labels: np.ndarray, preds: np.ndarray,
                     n_classes: int) -> np.ndarray:
    """Confusion counts (reference: MatrixPlotter input,
    veles/plotting_units.py:184)."""
    cm = np.zeros((n_classes, n_classes), np.int64)
    np.add.at(cm, (np.asarray(labels, np.int64),
                   np.asarray(preds, np.int64)), 1)
    return cm


def render_confusion(cm: np.ndarray, class_names=None) -> str:
    """Terminal confusion-matrix table."""
    n = cm.shape[0]
    names = class_names or [str(i) for i in range(n)]
    w = max(5, max(len(str(x)) for x in names) + 1)
    head = " " * w + "".join(f"{m:>{w}}" for m in names)
    rows = [head]
    for i in range(n):
        rows.append(f"{names[i]:>{w}}" + "".join(
            f"{cm[i, j]:>{w}}" for j in range(n)))
    return "\n".join(rows)


def histogram(values: np.ndarray, bins: int = 20, width: int = 40) -> str:
    """Terminal histogram (reference: Histogram plotter :536)."""
    hist, edges = np.histogram(np.asarray(values).ravel(), bins=bins)
    peak = hist.max() or 1
    lines = []
    for h, lo, hi in zip(hist, edges[:-1], edges[1:]):
        bar = "#" * int(width * h / peak)
        lines.append(f"[{lo:>10.3g}, {hi:>10.3g}) {bar} {h}")
    return "\n".join(lines)


def weights_image(weights: np.ndarray, grid=None) -> np.ndarray:
    """Tile first-layer weights into one image array (reference:
    ImagePlotter/Weights2D) — callers save via PIL/matplotlib."""
    w = np.asarray(weights)
    n, feat = w.shape[0], int(np.prod(w.shape[1:]))
    side = int(round(np.sqrt(feat)))
    if side * side != feat:
        return w  # not square-imageable
    if grid is None:
        gx = int(np.ceil(np.sqrt(n)))
        gy = int(np.ceil(n / gx))
    else:
        gx, gy = grid
    tiles = np.zeros((gy * side, gx * side), np.float32)
    for i in range(min(n, gx * gy)):
        r, c = divmod(i, gx)
        img = w[i].reshape(side, side)
        rng = img.max() - img.min() or 1.0
        tiles[r * side:(r + 1) * side, c * side:(c + 1) * side] = \
            (img - img.min()) / rng
    return tiles
