"""veles_tpu — a TPU-native deep-learning framework with the capabilities of
Samsung Veles (reference: /root/reference, mohnkhan/veles v0.9.2).

Not a port: the reference's thread-pool dataflow scheduler, OpenCL/CUDA
kernel JIT, mirrored host/device Arrays, and ZeroMQ master–slave data
parallelism (SURVEY.md §1) are re-designed as a functional SPMD framework:

* units are pure init/apply functions over pytrees (veles_tpu.units),
* a Workflow compiles the unit DAG into jitted train/eval XLA programs,
* ops target the MXU via jnp/lax, with Pallas kernels for fused hot paths,
* distribution is a jax.sharding Mesh + collectives over ICI/DCN
  (veles_tpu.parallel) instead of Twisted/ZMQ,
* checkpoints are explicit state pytrees (veles_tpu.runtime.Snapshotter).

Quick start::

    import veles_tpu as vt
    wf = vt.Workflow("mnist")
    wf.add(vt.units.All2AllTanh(100, name="fc1"))
    wf.add(vt.units.All2AllSoftmax(10, name="out", inputs=("fc1",)))
    wf.add(vt.units.EvaluatorSoftmax(name="ev",
                                     inputs=("out", "@labels", "@mask")))
    trainer = vt.Trainer(wf, loader, vt.optimizers.SGD(0.1, momentum=0.9),
                         vt.Decision(max_epochs=10))
    results = trainer.run()

The public namespace is LAZY (PEP 562 via the callable-module class):
``import veles_tpu`` binds no jax-heavy submodule until an attribute is
actually touched.  That keeps tooling that lives inside the package but
must never import the code it operates on — ``python -m
veles_tpu.analysis`` / ``veles-tpu-lint`` (docs/analysis.md) — a
millisecond-scale pure-stdlib import, and makes ``import veles_tpu``
cheap for everyone else.
"""

__version__ = "0.1.0"

import importlib as _importlib
import sys as _sys
import types as _types

#: public attribute -> (submodule, attribute-in-submodule or None for
#: the submodule itself).  This IS the package namespace; add new
#: public names here.
_LAZY = {
    # submodules
    "config": ("config", None),
    "logger": ("logger", None),
    "normalization": ("normalization", None),
    "ops": ("ops", None),
    "prng": ("prng", None),
    "units": ("units", None),
    "loader": ("loader", None),
    "runtime": ("runtime", None),
    "parallel": ("parallel", None),
    "models": ("models", None),
    "interaction": ("interaction", None),
    "publishing": ("publishing", None),
    "analysis": ("analysis", None),
    # re-exported symbols
    "Config": ("config", "Config"),
    "Range": ("config", "Range"),
    "root": ("config", "root"),
    "Logger": ("logger", "Logger"),
    "setup_logging": ("logger", "setup_logging"),
    "Spec": ("units", "Spec"),
    "Unit": ("units", "Unit"),
    "Workflow": ("units", "Workflow"),
    "optimizers": ("ops", "optimizers"),
    "ArrayLoader": ("loader", "ArrayLoader"),
    "FullBatchLoader": ("loader", "FullBatchLoader"),
    "Loader": ("loader", "Loader"),
    "ArtifactRunner": ("runtime", "ArtifactRunner"),
    "Decision": ("runtime", "Decision"),
    "DecodeEngine": ("runtime", "DecodeEngine"),
    "DeployController": ("runtime", "DeployController"),
    "Snapshotter": ("runtime", "Snapshotter"),
    "SnapshotterToDB": ("runtime", "SnapshotterToDB"),
    "StepCache": ("runtime", "StepCache"),
    "Trainer": ("runtime", "Trainer"),
    "generate": ("runtime", "generate"),
    "generate_beam": ("runtime", "generate_beam"),
    "MeshSpec": ("parallel", "MeshSpec"),
    "make_mesh": ("parallel", "make_mesh"),
    "StandardWorkflow": ("models", "StandardWorkflow"),
    "Publisher": ("publishing", "Publisher"),
}


#: PEP 562 pairing: star-import exports exactly the lazy namespace
#: (pre-refactor, the eager imports made these module globals).
__all__ = sorted(_LAZY)


def _resolve(name: str):
    mod_name, attr = _LAZY[name]
    module = _importlib.import_module(f"{__name__}.{mod_name}")
    value = module if attr is None else getattr(module, attr)
    globals()[name] = value         # cache: __getattr__ runs once
    return value


def __call_module__(config, *overrides, **kwargs):
    return _resolve("interaction").run(config, *overrides, **kwargs)


# Make the package itself callable — ``import veles_tpu; veles_tpu("cfg.py",
# "root.x=1")`` — the reference replaced its module with a callable
# VelesModule (veles/__init__.py:126-189); Python 3 allows swapping the
# module's class instead.  The same class hosts the lazy attribute
# protocol (a module-level __getattr__ would work too, but instance
# lookup beats module __getattr__ and this keeps one mechanism).
class _CallableModule(_types.ModuleType):
    __call__ = staticmethod(__call_module__)

    def __getattr__(self, name):
        if name in _LAZY:
            return _resolve(name)
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}")

    def __dir__(self):
        return sorted(set(super().__dir__()) | set(_LAZY))


_sys.modules[__name__].__class__ = _CallableModule
