"""veles_tpu — a TPU-native deep-learning framework with the capabilities of
Samsung Veles (reference: /root/reference, mohnkhan/veles v0.9.2).

Not a port: the reference's thread-pool dataflow scheduler, OpenCL/CUDA
kernel JIT, mirrored host/device Arrays, and ZeroMQ master–slave data
parallelism (SURVEY.md §1) are re-designed as a functional SPMD framework:

* units are pure init/apply functions over pytrees (veles_tpu.units),
* a Workflow compiles the unit DAG into jitted train/eval XLA programs,
* ops target the MXU via jnp/lax, with Pallas kernels for fused hot paths,
* distribution is a jax.sharding Mesh + collectives over ICI/DCN
  (veles_tpu.parallel) instead of Twisted/ZMQ,
* checkpoints are explicit state pytrees (veles_tpu.runtime.Snapshotter).

Quick start::

    import veles_tpu as vt
    wf = vt.Workflow("mnist")
    wf.add(vt.units.All2AllTanh(100, name="fc1"))
    wf.add(vt.units.All2AllSoftmax(10, name="out", inputs=("fc1",)))
    wf.add(vt.units.EvaluatorSoftmax(name="ev",
                                     inputs=("out", "@labels", "@mask")))
    trainer = vt.Trainer(wf, loader, vt.optimizers.SGD(0.1, momentum=0.9),
                         vt.Decision(max_epochs=10))
    results = trainer.run()
"""

__version__ = "0.1.0"

from . import config, logger, normalization, ops, prng
from .config import Config, Range, root
from .logger import Logger, setup_logging
from . import units
from .units import Spec, Unit, Workflow
from .ops import optimizers
from . import loader
from .loader import ArrayLoader, FullBatchLoader, Loader
from . import runtime
from .runtime import (ArtifactRunner, Decision, DecodeEngine,
                      DeployController, Snapshotter, SnapshotterToDB,
                      StepCache, Trainer, generate, generate_beam)
from . import parallel
from .parallel import MeshSpec, make_mesh
from . import models
from .models import StandardWorkflow
from . import interaction
from . import publishing
from .publishing import Publisher


def __call_module__(config, *overrides, **kwargs):
    return interaction.run(config, *overrides, **kwargs)


# Make the package itself callable — ``import veles_tpu; veles_tpu("cfg.py",
# "root.x=1")`` — the reference replaced its module with a callable
# VelesModule (veles/__init__.py:126-189); Python 3 allows swapping the
# module's class instead.
import sys as _sys
import types as _types


class _CallableModule(_types.ModuleType):
    __call__ = staticmethod(__call_module__)


_sys.modules[__name__].__class__ = _CallableModule
