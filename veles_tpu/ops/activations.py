"""Activation functions.

Covers the Znicz activation set (reference: docs
manualrst_veles_algorithms.rst:10-30 — all2all variants tanh/relu/softmax/
sincos). ``scaled_tanh`` is the classic 1.7159*tanh(2x/3) the 2014-era
frameworks used for FC nets; ``sincos`` alternates sin/cos over feature
index (Znicz's periodic activation).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def relu(x):
    return jnp.maximum(x, 0)


def scaled_tanh(x):
    return 1.7159 * jnp.tanh(0.6666 * x)


def sigmoid(x):
    return jax.nn.sigmoid(x)


def softmax(x, axis=-1):
    return jax.nn.softmax(x, axis=axis)


def log_softmax(x, axis=-1):
    return jax.nn.log_softmax(x, axis=axis)


def sincos(x):
    """Even feature indices -> sin, odd -> cos."""
    idx = jnp.arange(x.shape[-1])
    return jnp.where(idx % 2 == 0, jnp.sin(x), jnp.cos(x))


def identity(x):
    return x


ACTIVATIONS = {
    "linear": identity,
    "relu": relu,
    "tanh": scaled_tanh,
    "raw_tanh": jnp.tanh,
    "sigmoid": sigmoid,
    "sincos": sincos,
}


def rotary_embedding(x, *, base: float = 10000.0, offset: int = 0):
    """Rotary position embedding (RoPE) over (B, T, H, D) with even D:
    pairs (x[2i], x[2i+1]) rotate by angle pos / base^(2i/D).

    Elementwise in (pos, feature), so it is GSPMD-transparent: under
    sequence parallelism the T axis stays sharded and each shard rotates
    by its GLOBAL positions (offset + local index) without communication.
    """
    B, T, H, D = x.shape
    if D % 2:
        raise ValueError(f"RoPE needs an even head dim, got {D}")
    half = D // 2
    inv_freq = base ** (-jnp.arange(half, dtype=jnp.float32) / half)
    ang = (offset + jnp.arange(T, dtype=jnp.float32))[:, None] \
        * inv_freq[None, :]                      # (T, half)
    cos = jnp.cos(ang)[None, :, None, :].astype(x.dtype)
    sin = jnp.sin(ang)[None, :, None, :].astype(x.dtype)
    x1 = x[..., 0::2]
    x2 = x[..., 1::2]
    out = jnp.stack([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)
    return out.reshape(B, T, H, D)
