"""Activation functions.

Covers the Znicz activation set (reference: docs
manualrst_veles_algorithms.rst:10-30 — all2all variants tanh/relu/softmax/
sincos). ``scaled_tanh`` is the classic 1.7159*tanh(2x/3) the 2014-era
frameworks used for FC nets; ``sincos`` alternates sin/cos over feature
index (Znicz's periodic activation).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def relu(x):
    return jnp.maximum(x, 0)


def scaled_tanh(x):
    return 1.7159 * jnp.tanh(0.6666 * x)


def sigmoid(x):
    return jax.nn.sigmoid(x)


def softmax(x, axis=-1):
    return jax.nn.softmax(x, axis=axis)


def log_softmax(x, axis=-1):
    return jax.nn.log_softmax(x, axis=axis)


def sincos(x):
    """Even feature indices -> sin, odd -> cos."""
    idx = jnp.arange(x.shape[-1])
    return jnp.where(idx % 2 == 0, jnp.sin(x), jnp.cos(x))


def identity(x):
    return x


ACTIVATIONS = {
    "linear": identity,
    "relu": relu,
    "tanh": scaled_tanh,
    "raw_tanh": jnp.tanh,
    "sigmoid": sigmoid,
    "sincos": sincos,
}
