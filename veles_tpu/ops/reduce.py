"""Matrix row/column reduction (reference: ocl/matrix_reduce.cl:1-69,
cuda/matrix_reduce.cu — shared-memory tree reduction template). On TPU this
is ``jnp.sum``/``jnp.max`` over an axis; XLA emits the tree."""

from __future__ import annotations

import jax.numpy as jnp


def matrix_reduce(x, axis=0, op="sum"):
    fns = {"sum": jnp.sum, "max": jnp.max, "min": jnp.min, "mean": jnp.mean}
    return fns[op](x, axis=axis)
