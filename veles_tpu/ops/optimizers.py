"""Optimizers: SGD+momentum, AdaGrad, AdaDelta (+Adam as a bonus), with
learning-rate adjust policies, L1/L2 regularization, and per-layer
hyperparameter overrides.

Reference parity: Znicz gradient units supported exactly this set (docs
manualrst_veles_algorithms.rst:156-166 — items 3 lr-adjust policies,
5 L1/L2/custom regularization, and per-layer hyperparams). In the reference
each layer had its own "gradient descent unit" carrying its own lr/momentum;
here that becomes a per-unit override table applied over a single functional
optimizer — one fused XLA update over the whole parameter pytree instead of
one kernel launch per layer.

All update math runs in float32 regardless of the bf16 compute policy
(master-weight discipline for the MXU-friendly dtype split).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp


# -- learning-rate policies (reference item 3) ------------------------------

def fixed_lr(base: float) -> Callable[[jnp.ndarray], jnp.ndarray]:
    return lambda step: jnp.asarray(base, jnp.float32)


def exp_decay_lr(base: float, gamma: float, step_size: int = 1):
    """lr = base * gamma^(step // step_size)."""
    return lambda step: base * jnp.power(
        jnp.asarray(gamma, jnp.float32), step // step_size)


def inv_lr(base: float, gamma: float, power: float = 1.0):
    """lr = base / (1 + gamma*step)^power (caffe 'inv' policy)."""
    return lambda step: base * jnp.power(1.0 + gamma * step, -power)


def warmup_cosine_lr(base: float, warmup_steps: int, total_steps: int,
                     final_scale: float = 0.0):
    """Linear warmup to ``base`` over ``warmup_steps``, then cosine decay
    to ``final_scale * base`` at ``total_steps`` — the standard LM
    training schedule (no reference analog; its schedules were
    exp/inv/step, veles/znicz/gd.py lr_policy family)."""
    w = float(max(warmup_steps, 1))
    span = float(max(total_steps - warmup_steps, 1))

    def f(step):
        s = jnp.asarray(step, jnp.float32)
        warm = s / w
        prog = jnp.clip((s - warmup_steps) / span, 0.0, 1.0)
        cos = final_scale + (1.0 - final_scale) * 0.5 * (
            1.0 + jnp.cos(jnp.pi * prog))
        return base * jnp.where(s < warmup_steps, warm, cos)

    return f


def step_lr(base: float, boundaries, values):
    """Piecewise-constant schedule."""
    bounds = jnp.asarray(boundaries)
    vals = jnp.asarray([base] + list(values), jnp.float32)
    return lambda step: vals[jnp.searchsorted(bounds, step, side="right")]


LR_POLICIES = {
    "fixed": fixed_lr,
    "exp": exp_decay_lr,
    "inv": inv_lr,
    "step": step_lr,
    "warmup_cosine": warmup_cosine_lr,
}

#: Reserved opt_state key carrying the cumulative learning-rate multiplier
#: as a TRACED device scalar. Decision rollbacks multiply it by
#: ``rollback_lr_scale`` on the host and write the new value into the live
#: state — the compiled train step reads it every update, so an lr drop
#: never forces a re-trace/re-compile (the multiplier used to be baked
#: into a Python schedule closure, invalidating the whole XLA program).
#: Unit names cannot collide with it (dunder names are not valid units).
LR_MULT_KEY = "__lr_mult__"

#: Traced anomaly-sentinel counters riding opt_state next to the lr
#: multiplier (same recompile-free discipline): total updates skipped on
#: non-finite loss/grad-norm, and the CURRENT run of consecutive
#: anomalous steps (the Trainer's escalation gauge — it reads the value
#: once per epoch and rolls back when it crosses
#: ``root.common.train.anomaly_patience``).  Updated in-graph by
#: :func:`guarded_update`; carried through :meth:`Optimizer.update`
#: untouched so the state tree is structurally stable.
ANOM_SKIP_KEY = "__anom_skipped__"
ANOM_CONSEC_KEY = "__anom_consec__"

#: Reserved opt_state scalars and their neutral (fresh-state) values —
#: the one table legacy-snapshot adaptation walks (Trainer.restore
#: injects missing slots / drops surplus ones before the structural
#: tree-map).
def reserved_opt_neutral():
    import numpy as np
    return {LR_MULT_KEY: np.ones((), np.float32),
            ANOM_SKIP_KEY: np.zeros((), np.int32),
            ANOM_CONSEC_KEY: np.zeros((), np.int32)}


def global_grad_norm(grads) -> jnp.ndarray:
    """f32 global L2 norm over every gradient leaf (the quantity both
    the anomaly sentinel and global clipping key off)."""
    return jnp.sqrt(sum(
        jnp.sum(jnp.square(g.astype(jnp.float32)))
        for g in jax.tree.leaves(grads)) + 1e-12)


def _select_leaf(ok, new, old):
    """ok ? new : old for one leaf, PRNG-typed keys included."""
    if hasattr(new, "dtype") and jnp.issubdtype(new.dtype,
                                                jax.dtypes.prng_key):
        return jax.random.wrap_key_data(jnp.where(
            ok, jax.random.key_data(new), jax.random.key_data(old)))
    return jnp.where(ok, new, old)


def tree_select(ok, new_tree, old_tree):
    """Elementwise ``jnp.where(ok, new, old)`` over two same-structure
    pytrees — the sentinel's skip primitive (one fused select, no host
    sync, no recompile)."""
    return jax.tree.map(lambda n, o: _select_leaf(ok, n, o),
                        new_tree, old_tree)


def guarded_update(optimizer: "Optimizer", grads, opt_state, params,
                   step, loss, *, clip_norm: float = 0.0,
                   sentinel: bool = True, inject_nan_steps=()):
    """Anomaly-guarded optimizer update — the in-graph sentinel of the
    training fault-tolerance layer (docs/robustness.md).

    Runs ``optimizer.update`` and, when ``sentinel`` is on, SKIPS the
    whole update on a non-finite loss or gradient global norm: params
    and optimizer slots are carried through unchanged via a traced
    ``jnp.where`` select (no host sync per step, no recompile — the ok
    flag is data, not structure), and the ``ANOM_SKIP_KEY`` /
    ``ANOM_CONSEC_KEY`` opt_state scalars advance so the host can read
    skip totals once per epoch.  ``clip_norm > 0`` rescales gradients to
    that global norm first (``root.common.train.clip_norm``).
    ``inject_nan_steps`` is the fault harness's in-graph poison point
    (``runtime/faults.py::nan_grad_at_step``).

    Returns ``(params, opt_state, ok, gnorm)``; ``ok``/``gnorm`` are
    ``None`` when the corresponding machinery is off, so callers can
    gate metric sanitization on them.
    """
    if inject_nan_steps:
        bad_steps = jnp.asarray(tuple(inject_nan_steps), jnp.int32)
        hit = jnp.any(jnp.asarray(step, jnp.int32) == bad_steps)
        grads = jax.tree.map(
            lambda g: jnp.where(hit, jnp.asarray(jnp.nan, g.dtype), g)
            if jnp.issubdtype(jnp.asarray(g).dtype, jnp.inexact) else g,
            grads)
    clip_norm = float(clip_norm or 0.0)
    if not sentinel and clip_norm <= 0.0:
        p, s = optimizer.update(grads, opt_state, params, step)
        return p, s, None, None
    gnorm = global_grad_norm(grads)
    if clip_norm > 0.0:
        # a non-finite gnorm poisons the scale, but the ok-gate below
        # discards the whole update anyway — no need to special-case
        scale = jnp.minimum(1.0, jnp.asarray(clip_norm, jnp.float32)
                            / gnorm)
        grads = jax.tree.map(
            lambda g: (g.astype(jnp.float32) * scale).astype(g.dtype)
            if jnp.issubdtype(jnp.asarray(g).dtype, jnp.inexact) else g,
            grads)
    new_params, new_opt = optimizer.update(grads, opt_state, params, step)
    if not sentinel:
        return new_params, new_opt, None, gnorm
    ok = (jnp.isfinite(jnp.asarray(loss, jnp.float32))
          & jnp.isfinite(gnorm))
    sel_params = tree_select(ok, new_params, params)
    try:
        sel_opt = tree_select(ok, new_opt, opt_state)
    except ValueError:
        # legacy/optimizer-less states: update() lazily materialized
        # slots the input tree lacks, so the structures differ — take
        # the new tree (params above are still guarded)
        sel_opt = new_opt
    if isinstance(sel_opt, dict) and isinstance(opt_state, dict) \
            and ANOM_SKIP_KEY in opt_state:
        bad = (~ok).astype(jnp.int32)
        sel_opt = dict(sel_opt)
        sel_opt[ANOM_SKIP_KEY] = opt_state[ANOM_SKIP_KEY] + bad
        sel_opt[ANOM_CONSEC_KEY] = jnp.where(
            ok, jnp.zeros((), jnp.int32),
            opt_state[ANOM_CONSEC_KEY] + 1)
    return sel_params, sel_opt, ok, gnorm


@dataclasses.dataclass(frozen=True)
class HyperParams:
    """Per-layer tuning knobs (reference: per-layer lr/momentum/weight decay
    in gradient units). ``None`` = inherit the optimizer-wide value — so an
    explicit 0.0 *disables* that term for the layer."""
    lr_scale: float = 1.0          # multiplies the global schedule
    bias_lr_scale: Optional[float] = None
    l1: Optional[float] = None
    l2: Optional[float] = None     # weight decay (applied to grads)
    momentum: Optional[float] = None
    clip_norm: Optional[float] = None  # per-unit gradient-norm clip


class Optimizer:
    """Functional optimizer: ``state = init(params)``;
    ``params, state = update(grads, state, params, step)``.

    params is the workflow's nested {unit_name: {param_name: array}} dict;
    per-unit overrides are looked up by unit name.
    """

    def __init__(self, lr=0.01, *, lr_policy: Callable = None,
                 momentum: float = 0.0, l1: float = 0.0, l2: float = 0.0,
                 clip_norm: Optional[float] = None,
                 per_unit: Optional[Dict[str, HyperParams]] = None):
        self.schedule = lr_policy if lr_policy is not None else fixed_lr(lr)
        self.momentum = momentum
        self.l1 = l1
        self.l2 = l2
        self.clip_norm = clip_norm
        self.per_unit = dict(per_unit or {})

    # -- override in subclasses --------------------------------------------
    def init_slot(self, p) -> Any:
        return ()

    def apply_slot(self, g, slot, lr, hp, param=None) -> tuple:
        """Return (delta, new_slot); delta is subtracted from the param.
        ``param`` is the f32 master weight — optimizers with decoupled
        weight decay (AdamW) read it, the rest ignore it."""
        raise NotImplementedError

    # -- shared driver ------------------------------------------------------
    def init(self, params) -> Any:
        state = jax.tree.map(self.init_slot, params)
        if isinstance(state, dict):
            # the traced lr multiplier + anomaly counters ride opt_state
            # so they are sharded (replicated scalars), donated, and
            # checkpointed with the rest of the training state
            state[LR_MULT_KEY] = jnp.ones((), jnp.float32)
            state[ANOM_SKIP_KEY] = jnp.zeros((), jnp.int32)
            state[ANOM_CONSEC_KEY] = jnp.zeros((), jnp.int32)
        return state

    def _hp(self, unit_name: str) -> HyperParams:
        return self.per_unit.get(unit_name, HyperParams())

    def update(self, grads, state, params, step):
        lr = self.schedule(step)
        # The traced rollback multiplier: states from init() carry it;
        # legacy/empty states (init_state without an optimizer) fall back
        # to the plain schedule and keep their structure unchanged (the
        # step's output state must match its input sharding tree).
        lr_mult = state.get(LR_MULT_KEY) if isinstance(state, dict) \
            else None
        if lr_mult is not None:
            lr = lr * lr_mult
        if self.clip_norm is not None:
            gnorm = jnp.sqrt(sum(
                jnp.sum(jnp.square(g.astype(jnp.float32)))
                for g in jax.tree.leaves(grads)) + 1e-12)
            scale = jnp.minimum(1.0, self.clip_norm / gnorm)
            grads = jax.tree.map(lambda g: g * scale, grads)

        new_params, new_state = {}, {}
        if lr_mult is not None:
            new_state[LR_MULT_KEY] = lr_mult
        # anomaly counters pass through untouched — guarded_update (the
        # only writer) advances them AFTER the ok-select, keeping the
        # state tree structurally identical in and out
        if isinstance(state, dict):
            for k in (ANOM_SKIP_KEY, ANOM_CONSEC_KEY):
                if k in state:
                    new_state[k] = state[k]
        for uname, uparams in params.items():
            hp = self._hp(uname)
            ugrads = grads[uname]
            # Tolerate state from init_state(key) without an optimizer —
            # missing slots initialize to zero on first trace
            # (lazily, per leaf, inside _update_tree).
            ustate = state.get(uname) or {}
            if hp.clip_norm is not None:
                unorm = jnp.sqrt(sum(
                    jnp.sum(jnp.square(g.astype(jnp.float32)))
                    for g in jax.tree.leaves(ugrads)) + 1e-12)
                uscale = jnp.minimum(1.0, hp.clip_norm / unorm)
            else:
                uscale = None
            new_params[uname], new_state[uname] = self._update_tree(
                uparams, ugrads, ustate, hp, lr, uscale)
        return new_params, new_state

    def _update_tree(self, uparams, ugrads, ustate, hp, lr, uscale):
        """Recursive leaf update: unit params are usually a flat
        name->array dict, but may nest (PipelineStack config stages hold
        one subtree per stage); slots mirror whatever the structure is."""
        np_, ns_ = {}, {}
        for pname, p in uparams.items():
            if isinstance(p, dict):
                sub = ustate.get(pname)
                if not isinstance(sub, dict):
                    sub = {}  # leaves lazily init in the recursive call
                np_[pname], ns_[pname] = self._update_tree(
                    p, ugrads[pname], sub, hp, lr, uscale)
                continue
            g = ugrads[pname].astype(jnp.float32)
            if uscale is not None:
                g = g * uscale
            p32 = p.astype(jnp.float32)
            l1 = hp.l1 if hp.l1 is not None else self.l1
            l2 = hp.l2 if hp.l2 is not None else self.l2
            if l2:
                g = g + l2 * p32
            if l1:
                g = g + l1 * jnp.sign(p32)
            scale = hp.lr_scale
            if pname == "b" and hp.bias_lr_scale is not None:
                scale = hp.bias_lr_scale
            slot0 = ustate.get(pname, None)
            if slot0 is None:
                slot0 = self.init_slot(p)
            delta, slot = self.apply_slot(g, slot0, lr * scale, hp,
                                          param=p32)
            np_[pname] = (p32 - delta).astype(p.dtype)
            ns_[pname] = slot
        return np_, ns_


class SGD(Optimizer):
    """SGD with classical momentum (reference: Znicz GD units).

    Velocity slots are allocated when the global OR any per-unit momentum is
    nonzero, so per-layer momentum overrides work with momentum=0 globally."""

    def _uses_momentum(self) -> bool:
        return bool(self.momentum) or any(
            hp.momentum for hp in self.per_unit.values()
            if hp.momentum is not None)

    def init_slot(self, p):
        return jnp.zeros(p.shape, jnp.float32) if self._uses_momentum() \
            else ()

    def apply_slot(self, g, slot, lr, hp, param=None):
        mom = hp.momentum if hp.momentum is not None else self.momentum
        if isinstance(slot, tuple):  # no velocity allocated
            return lr * g, ()
        v = mom * slot + g
        return lr * v, v


class AdaGrad(Optimizer):
    def __init__(self, lr=0.01, eps=1e-8, **kw):
        super().__init__(lr, **kw)
        self.eps = eps

    def init_slot(self, p):
        return jnp.zeros(p.shape, jnp.float32)

    def apply_slot(self, g, slot, lr, hp, param=None):
        acc = slot + jnp.square(g)
        return lr * g / (jnp.sqrt(acc) + self.eps), acc


class AdaDelta(Optimizer):
    def __init__(self, lr=1.0, rho=0.95, eps=1e-6, **kw):
        super().__init__(lr, **kw)
        self.rho = rho
        self.eps = eps

    def init_slot(self, p):
        return (jnp.zeros(p.shape, jnp.float32),
                jnp.zeros(p.shape, jnp.float32))

    def apply_slot(self, g, slot, lr, hp, param=None):
        acc_g, acc_d = slot
        acc_g = self.rho * acc_g + (1 - self.rho) * jnp.square(g)
        delta = g * jnp.sqrt(acc_d + self.eps) / jnp.sqrt(acc_g + self.eps)
        acc_d = self.rho * acc_d + (1 - self.rho) * jnp.square(delta)
        return lr * delta, (acc_g, acc_d)


class Adam(Optimizer):
    """Not in the reference set; included because the rebuild's model zoo
    (and any modern user) needs it."""

    def __init__(self, lr=1e-3, b1=0.9, b2=0.999, eps=1e-8, **kw):
        super().__init__(lr, **kw)
        self.b1, self.b2, self.eps = b1, b2, eps

    def init_slot(self, p):
        return (jnp.zeros(p.shape, jnp.float32),
                jnp.zeros(p.shape, jnp.float32),
                jnp.zeros((), jnp.float32))

    def apply_slot(self, g, slot, lr, hp, param=None):
        m, v, t = slot
        t = t + 1
        m = self.b1 * m + (1 - self.b1) * g
        v = self.b2 * v + (1 - self.b2) * jnp.square(g)
        mhat = m / (1 - jnp.power(self.b1, t))
        vhat = v / (1 - jnp.power(self.b2, t))
        return lr * mhat / (jnp.sqrt(vhat) + self.eps), (m, v, t)


class AdamW(Adam):
    """Adam with DECOUPLED weight decay (Loshchilov & Hutter, 2019): the
    decay is applied to the parameter directly, outside the adaptive
    moments — the LM-training standard. ``l2`` (coupled decay through
    the gradient) is rejected to prevent silently mixing the two."""

    def __init__(self, lr=1e-3, weight_decay: float = 0.01, **kw):
        if kw.get("l2"):
            raise ValueError(
                "AdamW takes decoupled weight_decay=, not l2 (which "
                "would couple the decay through the adaptive moments)")
        coupled = [n for n, hp in (kw.get("per_unit") or {}).items()
                   if hp.l2]
        if coupled:
            raise ValueError(
                f"per-unit hyperparams l2 on {coupled} would apply "
                "COUPLED decay under AdamW; use the optimizer-wide "
                "weight_decay (or switch those layers to Adam + l2)")
        super().__init__(lr, **kw)
        self.weight_decay = float(weight_decay)

    def apply_slot(self, g, slot, lr, hp, param=None):
        delta, slot = super().apply_slot(g, slot, lr, hp)
        return delta + lr * self.weight_decay * param, slot


OPTIMIZERS = {
    "sgd": SGD,
    "momentum": lambda lr=0.01, **kw: SGD(lr, momentum=kw.pop("momentum", 0.9), **kw),
    "adagrad": AdaGrad,
    "adadelta": AdaDelta,
    "adam": Adam,
    "adamw": AdamW,
}
