"""Convolution / deconvolution ops.

The reference's conv/deconv lived in the absent Znicz submodule (reference:
docs manualrst_veles_algorithms.rst:31-60; padding/stride at :167 item 14).
On TPU these are XLA's native convs — ``lax.conv_general_dilated`` hits the
MXU directly with NHWC layout; no hand kernel can beat it for dense convs,
so Pallas is reserved for fused exotica (see ops/pallas_kernels.py).
"""

from __future__ import annotations

from .linear import config_precision

import jax
import jax.numpy as jnp

DIMS = ("NHWC", "HWIO", "NHWC")


def _pair(v):
    return (v, v) if isinstance(v, int) else tuple(v)


def conv2d(x, w, b=None, *, stride=1, padding="SAME", precision=None,
           compute_dtype=None):
    """x: (N,H,W,C), w: (kh,kw,Cin,Cout).

    Under the bf16 policy inputs are cast down and the conv runs in bf16:
    the TPU MXU accumulates bf16 convolutions in float32 in hardware, so no
    preferred_element_type is forced (doing so breaks the conv gradient
    rule, which requires matching operand dtypes)."""
    out_dtype = jnp.result_type(x.dtype, w.dtype)
    preferred = jnp.float32
    if compute_dtype is not None:
        x = x.astype(compute_dtype)
        w = w.astype(compute_dtype)
        preferred = None
    if isinstance(padding, int):
        p = _pair(padding)
        padding = ((p[0], p[0]), (p[1], p[1]))
    y = jax.lax.conv_general_dilated(
        x, w, window_strides=_pair(stride), padding=padding,
        dimension_numbers=DIMS,
        precision=config_precision() if precision is None else precision,
        preferred_element_type=preferred)
    y = y.astype(out_dtype)
    if b is not None:
        y = y + b
    return y


def deconv2d(x, w, b=None, *, stride=1, padding="SAME", precision=None,
             compute_dtype=None):
    """Transposed conv (reference Znicz 'deconv'). Same dtype policy as
    conv2d: bf16 operands rely on MXU f32 accumulation."""
    out_dtype = jnp.result_type(x.dtype, w.dtype)
    preferred = jnp.float32
    if compute_dtype is not None:
        x = x.astype(compute_dtype)
        w = w.astype(compute_dtype)
        preferred = None
    if isinstance(padding, int):
        p = _pair(padding)
        padding = ((p[0], p[0]), (p[1], p[1]))
    y = jax.lax.conv_transpose(
        x, w, strides=_pair(stride), padding=padding,
        dimension_numbers=DIMS,
        precision=config_precision() if precision is None else precision,
        preferred_element_type=preferred)
    y = y.astype(out_dtype)
    if b is not None:
        y = y + b
    return y
