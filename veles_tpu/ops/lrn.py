"""Local response normalization across channels (reference Znicz LRN,
docs manualrst_veles_algorithms.rst:31-60; AlexNet-style).

y = x / (k + alpha/n * sum_{j in window} x_j^2)^beta over the channel axis.
Implemented with a window sum XLA fuses into neighboring ops.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def local_response_norm(x, *, n=5, k=2.0, alpha=1e-4, beta=0.75):
    """x: (..., C). AlexNet semantics: alpha is divided by window size n."""
    sq = jnp.square(x)
    half = n // 2
    # Pad channels and window-sum with reduce_window over the last axis.
    pads = [(0, 0)] * (x.ndim - 1) + [(half, n - 1 - half)]
    sq = jnp.pad(sq, pads)
    window = (1,) * (x.ndim - 1) + (n,)
    strides = (1,) * x.ndim
    ssum = jax.lax.reduce_window(sq, 0.0, jax.lax.add, window, strides,
                                 "VALID")
    return x * jax.lax.pow(k + (alpha / n) * ssum, -beta)
