"""Local response normalization across channels (reference Znicz LRN,
docs manualrst_veles_algorithms.rst:31-60; AlexNet-style).

y = x / (k + alpha/n * sum_{j in window} x_j^2)^beta over the channel axis.

TPU-first implementation: the channel-window sum runs as a **band-matrix
matmul on the MXU** — a windowed reduction over the minor (lane) axis is
the VPU's worst case (`reduce_window` measured ~1.5x slower end-to-end on
AlexNet's LRN layers), while an (C, C) 0/1 band contraction is almost free
on the systolic array.  The beta=0.75 power runs as rsqrt(y*sqrt(y)) — two
sqrts instead of exp+log."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .linear import config_precision

# Above this channel count the C×C band matrix stops being "almost free";
# fall back to reduce_window.
_BAND_MATMUL_MAX_C = 2048


def _window_sum(sq, n: int):
    c = sq.shape[-1]
    half = n // 2
    if c <= _BAND_MATMUL_MAX_C:
        idx = jnp.arange(c)
        # Asymmetric window of exactly n: out_i sums sq[j] for
        # j - i in [-half, n-1-half], matching the reduce_window pad
        # below for even n too. In sq @ band, band[j, i] pairs row j with
        # output i, and (idx[None,:]-idx[:,None])[j, i] = i - j.
        diff = idx[None, :] - idx[:, None]
        band = ((diff >= -(n - 1 - half)) & (diff <= half)).astype(sq.dtype)
        # The C×C band contraction is cheap; never let a DEFAULT bf16 MXU
        # pass truncate the f32 squared activations (advisor r1). Honour
        # the precision_level knob, but floor it at HIGH.
        prec = config_precision()
        if prec == jax.lax.Precision.DEFAULT:
            prec = jax.lax.Precision.HIGH
        return jax.lax.dot_general(
            sq.reshape(-1, c), band, (((1,), (0,)), ((), ())),
            precision=prec,
            preferred_element_type=jnp.float32).reshape(sq.shape)
    pads = [(0, 0)] * (sq.ndim - 1) + [(half, n - 1 - half)]
    return jax.lax.reduce_window(
        jnp.pad(sq, pads), 0.0, jax.lax.add,
        (1,) * (sq.ndim - 1) + (n,), (1,) * sq.ndim, "VALID")


def local_response_norm(x, *, n=5, k=2.0, alpha=1e-4, beta=0.75):
    """x: (..., C). AlexNet semantics: alpha is divided by window size n."""
    ssum = _window_sum(jnp.square(x), n)
    y = k + (alpha / n) * ssum
    if beta == 0.75:
        out = x * jax.lax.rsqrt(y * jnp.sqrt(y))
    elif beta == 0.5:
        out = x * jax.lax.rsqrt(y)
    elif beta == 1.0:
        out = x / y
    else:
        out = x * jax.lax.pow(y, -beta)
    # The band-matmul accumulates in f32; keep the layer dtype-preserving
    # (build-time specs and bf16 activation bandwidth depend on it).
    return out.astype(x.dtype)
