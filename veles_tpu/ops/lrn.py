"""Local response normalization across channels (reference Znicz LRN,
docs manualrst_veles_algorithms.rst:31-60; AlexNet-style).

y = x / (k + alpha/n * sum_{j in window} x_j^2)^beta over the channel axis.

TPU-first implementation: the channel-window sum defaults to an **exact
f32 cumsum difference** (two VPU passes, zero MXU time, no precision
knob); the round-1 design — a (C, C) 0/1 **band-matrix matmul on the
MXU** — stays selectable (``method="band"``) for A/B and for the
reduce_window fallback above ``_BAND_MATMUL_MAX_C`` channels. A naive
windowed reduction over the minor (lane) axis is the VPU's worst case
(`reduce_window` measured ~1.5x slower end-to-end on AlexNet's LRN
layers). The beta=0.75 power runs as rsqrt(y*sqrt(y)) — two sqrts
instead of exp+log."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .linear import config_precision

# Above this channel count the C×C band matrix stops being "almost free";
# fall back to reduce_window.
_BAND_MATMUL_MAX_C = 2048


def _window_sum_cumsum(sq, n: int):
    """Windowed channel sum as a cumsum difference: two exact f32 VPU
    passes instead of a C×C matmul — no MXU time and no precision knob
    (measured A/B against the band matmul in bench_tpu.py/profiling; the
    band form cost ~HIGH-precision matmul FLOPs on AlexNet's LRN layers).
    Cancellation error is O(C·eps) — negligible inside k + alpha/n·sum."""
    half = n // 2
    up = n - 1 - half   # window: j - i in [-half, up] (same as the band)
    cs = jnp.cumsum(sq.astype(jnp.float32), axis=-1)
    pads = [(0, 0)] * (sq.ndim - 1)
    # sum_{j=i-half}^{i+up} sq[j] = cs[min(i+up, C-1)] - cs[i-half-1]
    hi = jnp.pad(cs, pads + [(0, up)], mode="edge")[..., up:]
    lo = jnp.pad(cs, pads + [(half + 1, 0)])[..., :cs.shape[-1]]
    return hi - lo


def _window_sum(sq, n: int, method: str = "cumsum"):
    if method not in ("cumsum", "band", "band_bf16"):
        raise ValueError(f"LRN method must be 'cumsum', 'band' or "
                         f"'band_bf16', got {method!r}")
    c = sq.shape[-1]
    half = n // 2
    if method == "cumsum":
        return _window_sum_cumsum(sq, n)
    if c <= _BAND_MATMUL_MAX_C:
        idx = jnp.arange(c)
        # Asymmetric window of exactly n: out_i sums sq[j] for
        # j - i in [-half, n-1-half], matching the reduce_window pad
        # below for even n too. In sq @ band, band[j, i] pairs row j with
        # output i, and (idx[None,:]-idx[:,None])[j, i] = i - j.
        diff = idx[None, :] - idx[:, None]
        mask = (diff >= -(n - 1 - half)) & (diff <= half)
        if method == "band_bf16":
            # Single-pass MXU rate: squared activations quantized to
            # bf16 (~0.4% relative), 0/1 band exact in bf16, f32
            # accumulation. Sound for LRN because the window sum only
            # perturbs the denominator k + (alpha/n)·ssum — at AlexNet's
            # alpha=1e-4 a 0.4% error on ssum moves y by ~1e-6 relative.
            # This is the round-1 formulation that measured +22% AlexNet
            # throughput before the precision floor below made the f32
            # band cost 3 MXU passes (BASELINE.md AlexNet r3 row).
            operand = sq.reshape(-1, c).astype(jnp.bfloat16)
            band = mask.astype(jnp.bfloat16)
            prec = None
        else:
            # The f32 C×C band contraction must not let a DEFAULT bf16
            # MXU pass truncate the f32 squared activations SILENTLY
            # (advisor r1): honour the precision_level knob but floor it
            # at HIGH. Callers who accept the (benign, see above) bf16
            # quantization say so explicitly with method="band_bf16".
            operand = sq.reshape(-1, c)
            band = mask.astype(sq.dtype)
            prec = config_precision()
            if prec == jax.lax.Precision.DEFAULT:
                prec = jax.lax.Precision.HIGH
        return jax.lax.dot_general(
            operand, band, (((1,), (0,)), ((), ())), precision=prec,
            preferred_element_type=jnp.float32).reshape(sq.shape)
    pads = [(0, 0)] * (sq.ndim - 1) + [(half, n - 1 - half)]
    return jax.lax.reduce_window(
        jnp.pad(sq, pads), 0.0, jax.lax.add,
        (1,) * (sq.ndim - 1) + (n,), (1,) * sq.ndim, "VALID")


def local_response_norm(x, *, n=5, k=2.0, alpha=1e-4, beta=0.75,
                        method="cumsum"):
    """x: (..., C). AlexNet semantics: alpha is divided by window size n.
    ``method``: "cumsum" (default; exact f32, VPU-only), "band" (C×C 0/1
    matmul on the MXU at >=HIGH precision) or "band_bf16" (same band at
    single-pass MXU rate with bf16-quantized inputs + f32 accumulation —
    benign for the LRN denominator, see _window_sum)."""
    ssum = _window_sum(jnp.square(x), n, method)
    y = k + (alpha / n) * ssum
    if beta == 0.75:
        out = x * jax.lax.rsqrt(y * jnp.sqrt(y))
    elif beta == 0.5:
        out = x * jax.lax.rsqrt(y)
    elif beta == 1.0:
        out = x / y
    else:
        out = x * jax.lax.pow(y, -beta)
    # The band-matmul accumulates in f32; keep the layer dtype-preserving
    # (build-time specs and bf16 activation bandwidth depend on it).
    return out.astype(x.dtype)
