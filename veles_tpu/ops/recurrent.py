"""Recurrent cells compiled with lax.scan.

Reference capability: Znicz declared RNN/LSTM units ("created but not
tested", reference: docs/source/manualrst_veles_algorithms.rst:115-134);
this rebuild implements them properly, TPU-first:

* the time loop is a ``lax.scan`` — a single compiled loop, no Python
  unrolling, so compile time stays flat with sequence length;
* all gates of a step are computed by ONE fused gemm over the concatenated
  ``[x, h]`` — a (B, F+H) x (F+H, G*H) matmul that tiles onto the MXU,
  instead of G small matmuls;
* an optional ``compute_dtype`` (bf16) casts the gemm operands while the
  carried state stays f32 — f32 carry keeps long-sequence recurrences from
  drifting, matching the framework-wide "bf16 compute / f32 master" policy.

Scan is over the leading (time) axis; inputs are (T, B, F) internally and
transposed at the unit boundary, so the batch dimension stays the gemm's
row dimension every step.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp


def _gates_matmul(x, h, w, b, compute_dtype):
    """One fused (B, F+H) @ (F+H, G*H) gemm for all gates of a step."""
    xh = jnp.concatenate([x, h], axis=-1)
    if compute_dtype is not None:
        y = jnp.dot(xh.astype(compute_dtype), w.astype(compute_dtype),
                    preferred_element_type=jnp.float32)
    else:
        y = jnp.dot(xh, w)
    return y + b


def rnn_cell(x, h, w, b, *, activation=jnp.tanh, compute_dtype=None):
    """One Elman step: (B, F), (B, H) -> new h (B, H).  Shared by the
    training scan and the O(1)-state autoregressive decode
    (runtime/generate.py) so the two paths cannot drift numerically."""
    return activation(_gates_matmul(x, h, w, b, compute_dtype))


def gru_cell(x, h, w, b, *, compute_dtype=None):
    """One GRU step: fused [reset, update] gemm + candidate gemm on r*h."""
    hidden = h.shape[-1]
    w_rz, w_cand = w[:, :2 * hidden], w[:, 2 * hidden:]
    b_rz, b_cand = b[:2 * hidden], b[2 * hidden:]
    rz = jax.nn.sigmoid(_gates_matmul(x, h, w_rz, b_rz, compute_dtype))
    r, z = jnp.split(rz, 2, axis=-1)
    c = jnp.tanh(_gates_matmul(x, r * h, w_cand, b_cand, compute_dtype))
    return (1.0 - z) * h + z * c


def lstm_cell(x, h, c, w, b, *, compute_dtype=None,
              forget_bias: float = 1.0):
    """One LSTM step -> (new h, new c)."""
    gates = _gates_matmul(x, h, w, b, compute_dtype)
    i, f, g, o = jnp.split(gates, 4, axis=-1)
    i = jax.nn.sigmoid(i)
    f = jax.nn.sigmoid(f + forget_bias)
    g = jnp.tanh(g)
    o = jax.nn.sigmoid(o)
    c_new = f * c + i * g
    return o * jnp.tanh(c_new), c_new


def rnn_scan(xs: jax.Array, h0: jax.Array, w: jax.Array, b: jax.Array,
             *, activation=jnp.tanh, compute_dtype=None
             ) -> Tuple[jax.Array, jax.Array]:
    """Simple (Elman) RNN. xs: (T, B, F); w: (F+H, H); returns
    (ys (T, B, H), h_T)."""

    def step(h, x):
        h_new = rnn_cell(x, h, w, b, activation=activation,
                         compute_dtype=compute_dtype)
        return h_new, h_new

    h_final, ys = jax.lax.scan(step, h0, xs)
    return ys, h_final


def gru_scan(xs: jax.Array, h0: jax.Array, w: jax.Array, b: jax.Array,
             *, compute_dtype=None) -> Tuple[jax.Array, jax.Array]:
    """GRU. w: (F+H, 3H) for [reset, update, candidate] gates; the candidate
    uses r*h, so its slice is applied in a second small gemm on the gated
    hidden only when needed — here we follow the standard fused variant
    (candidate weights split into x- and h- halves)."""

    def step(h, x):
        h_new = gru_cell(x, h, w, b, compute_dtype=compute_dtype)
        return h_new, h_new

    h_final, ys = jax.lax.scan(step, h0, xs)
    return ys, h_final


def lstm_scan(xs: jax.Array, h0: jax.Array, c0: jax.Array,
              w: jax.Array, b: jax.Array, *, compute_dtype=None,
              forget_bias: float = 1.0
              ) -> Tuple[jax.Array, Tuple[jax.Array, jax.Array]]:
    """LSTM. w: (F+H, 4H) for [input, forget, cell, output] gates in one
    gemm. forget_bias is added to the forget gate pre-activation (standard
    trick for gradient flow at init)."""

    def step(carry, x):
        h, c = carry
        h_new, c_new = lstm_cell(x, h, c, w, b,
                                 compute_dtype=compute_dtype,
                                 forget_bias=forget_bias)
        return (h_new, c_new), h_new

    (h_final, c_final), ys = jax.lax.scan(step, (h0, c0), xs)
    return ys, (h_final, c_final)


def rnn_reference(xs, h0, w, b, activation=None):
    """Numpy-semantics reference for tests (same math, plain loop)."""
    import numpy as np
    act = np.tanh if activation is None else activation
    h = np.asarray(h0, np.float64)
    w64, b64 = np.asarray(w, np.float64), np.asarray(b, np.float64)
    ys = []
    for x in np.asarray(xs, np.float64):
        h = act(np.concatenate([x, h], axis=-1) @ w64 + b64)
        ys.append(h)
    return np.stack(ys), h
