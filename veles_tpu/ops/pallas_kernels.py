"""Hand-written Pallas TPU kernels for the ops where fusion semantics or
memory movement matter beyond what XLA's automatic fusion gives.

Reference parity (each kernel names its OpenCL/CUDA counterpart):

* ``flash_attention``        — no reference counterpart (SURVEY.md §5.7: the
  reference has no attention); TPU-native blockwise-softmax kernel.  The
  long-context path (parallel/ring_attention.py ``blockwise_attention``)
  delegates to it on TPU.
* ``fused_dropout``          — reference: Znicz dropout unit backed by the
  parallel RNG kernels ``ocl/random.cl`` / ``cuda/random.cu`` (xorshift1024*
  per-state, interleaved output).  Here the RNG is a counter-based
  splitmix32 hash of (seed, linear element index) generated *inside* the
  kernel, so mask bits never touch HBM and the backward pass can regenerate
  them exactly instead of storing the mask.
* ``mean_disp_normalize``    — reference: ``ocl/mean_disp_normalizer.cl`` /
  ``cuda/mean_disp_normalizer.cu`` ((uint8 x − mean) · rdisp elementwise).
* ``gather_rows``            — reference: ``ocl/fullbatch_loader.cl``
  ``fill_minibatch_data_labels`` (minibatch gather from the on-device
  dataset by shuffled indices).  TPU version: scalar-prefetched indices
  drive the BlockSpec index_map, so each minibatch row is a direct
  HBM→VMEM DMA — the dataset itself never streams through compute.

All kernels run compiled on TPU and in interpreter mode elsewhere (tests run
them on the CPU backend with ``interpret=True``; see tests/test_pallas.py).
"""

from __future__ import annotations

import functools
from typing import Optional

import inspect

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


from . import (use_pallas_default,  # policy lives pallas-free in ops/__init__
               check_attention_window, check_gqa_heads)

#: ``pltpu.CompilerParams`` across jax versions (the parallel/mesh.py
#: ``shard_map`` shim pattern): older jax names the class
#: ``TPUCompilerParams`` and lacks some fields (e.g.
#: ``has_side_effects``).  Fields the resident class does not know are
#: DROPPED — they are Mosaic lowering hints, not kernel semantics, and
#: the kernels here run interpret-mode wherever the old class exists
#: without them (the CPU test tier), so a missing hint can never change
#: results.
_COMPILER_PARAMS_CLS = getattr(pltpu, "CompilerParams", None) \
    or pltpu.TPUCompilerParams
_COMPILER_PARAMS_FIELDS = frozenset(
    inspect.signature(_COMPILER_PARAMS_CLS).parameters)


def compiler_params(**kwargs):
    """Version-portable ``pltpu.CompilerParams(**kwargs)``."""
    return _COMPILER_PARAMS_CLS(**{k: v for k, v in kwargs.items()
                                   if k in _COMPILER_PARAMS_FIELDS})


#: ``pltpu.HBM`` across jax versions: older jax only exposes the ANY
#: memory space, which is how its pallas lowering says "leave the
#: operand in HBM / let the DMA address it" — the same contract the
#: gather kernel wants from HBM.
_HBM = getattr(pltpu, "HBM", None) or pltpu.ANY


def _interpret(interpret: Optional[bool]) -> bool:
    if interpret is None:
        return not use_pallas_default()
    return interpret


def _round_up(n: int, m: int) -> int:
    return -(-n // m) * m


# ---------------------------------------------------------------------------
# Flash attention (forward kernel + recompute backward)
# ---------------------------------------------------------------------------

def _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_ref, l_ref,
                      acc_ref, *,
                      scale, causal, window, block_q, block_k, tq, tk,
                      n_kb):
    """Grid = (BH, n_q_blocks, n_k_blocks); the k dimension is minor, so
    VMEM holds only one (block_q, D) Q tile and one (block_k, D) K/V tile at
    a time — the m/l/acc online-softmax state lives in scratch that persists
    across the sequentially-iterated k steps (long T streams from HBM
    block-by-block instead of residing whole in VMEM)."""
    qi, kj = pl.program_id(1), pl.program_id(2)

    @pl.when(kj == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, -1e30)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    def _step():
        # Operands stay in their storage dtype (bf16 inputs hit the MXU at
        # the bf16 rate); accumulation is forced to f32 via
        # preferred_element_type — casting to f32 first would silently run
        # the matmuls at the several-times-slower f32 MXU rate.
        q = q_ref[0]  # (block_q, D)
        k_blk = k_ref[0]  # (block_k, D)
        v_blk = v_ref[0]
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        k_pos = kj * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        mask = k_pos < tk
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            mask = mask & (k_pos <= q_pos)
            if window is not None:
                # sliding window: key positions in (q - window, q]
                mask = mask & (k_pos > q_pos - window)
        s = jnp.where(mask, s, -1e30)
        # Row state m/l is kept as (block_q, 1) column vectors — keepdims
        # math throughout, because Mosaic's layout rules want >=2-D values
        # (rank-2 with a unit minor dim lowers cleanly; rank-1 does not).
        m = m_ref[:]
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m - m_new)
        p = jnp.where(mask, jnp.exp(s - m_new), 0.0)
        m_ref[:] = m_new
        l_ref[:] = alpha * l_ref[:] + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p.astype(v_blk.dtype), v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    # Skip fully-masked K blocks: with causal, those after the diagonal;
    # with a sliding window also those entirely before it — cost becomes
    # O(T*window) instead of O(T^2/2).
    live = _flash_block_live(qi, kj, causal=causal, window=window,
                             block_q=block_q, block_k=block_k)
    if live is None:
        _step()
    else:
        pl.when(live)(_step)

    @pl.when(kj == n_kb - 1)
    def _finalize():
        o_ref[0] = (acc_ref[:]
                    / jnp.maximum(l_ref[:], 1e-30)).astype(o_ref.dtype)
        # logsumexp per row, consumed by the Pallas backward kernels.
        # Stored as (BH, T, 1): the unit minor dim keeps the block shape
        # legal under Mosaic's (8, 128)-divisible-or-full rule.
        lse_ref[0] = m_ref[:] + jnp.log(jnp.maximum(l_ref[:], 1e-30))


def _flash_layout(x, T, t_p):
    """(B, T, H, D) -> (B*H, t_p, D) with the T axis zero-padded."""
    B, _, H, D = x.shape
    return jnp.pad(x.transpose(0, 2, 1, 3).reshape(B * H, T, D),
                   ((0, 0), (0, t_p - T), (0, 0)))


def _flash_blocks(Tq, Tk, block_q, block_k):
    block_q = min(block_q, _round_up(Tq, 8))
    block_k = min(block_k, _round_up(Tk, 8))
    return block_q, block_k, _round_up(Tq, block_q), _round_up(Tk, block_k)


def _gqa_groups(q, k):
    """Grouped-query attention factor: q heads per kv head.  H == H_kv is
    plain MHA (G=1)."""
    return check_gqa_heads(q.shape[2], k.shape[2])


def _kv_row_map(H, H_kv, G):
    """Grid-index map from q-head row b to its shared kv row — the ONE
    definition both the forward and the dq kernel use (drift here would
    make fwd and bwd read different kv blocks)."""
    if G == 1:
        return lambda b: b
    return lambda b: (b // H) * H_kv + (b % H) // G


def _flash_fwd(q, k, v, *, causal, scale, block_q, block_k, interpret,
               window=None, return_lse=False):
    B, Tq, H, D = q.shape
    Tk = k.shape[1]
    H_kv = k.shape[2]
    G = _gqa_groups(q, k)
    window = check_attention_window(window, causal)
    scale_ = scale if scale is not None else D ** -0.5
    block_q, block_k, tq_p, tk_p = _flash_blocks(Tq, Tk, block_q, block_k)

    qm = _flash_layout(q, Tq, tq_p)
    km = _flash_layout(k, Tk, tk_p)
    vm = _flash_layout(v, Tk, tk_p)

    n_kb = tk_p // block_k
    kernel = functools.partial(
        _flash_fwd_kernel, scale=scale_, causal=causal, window=window,
        block_q=block_q, block_k=block_k, tq=Tq, tk=Tk, n_kb=n_kb)
    # GQA: index-map arithmetic on grid indices is static.
    kv_row = _kv_row_map(H, H_kv, G)
    out, lse = pl.pallas_call(
        kernel,
        grid=(B * H, tq_p // block_q, n_kb),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, D),
                         lambda b, i, j: (kv_row(b), j, 0)),
            pl.BlockSpec((1, block_k, D),
                         lambda b, i, j: (kv_row(b), j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * H, tq_p, D), q.dtype),
            jax.ShapeDtypeStruct((B * H, tq_p, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, D), jnp.float32),
        ],
        # batch*head and q-block steps are independent; only the k sweep
        # carries the online-softmax state — telling Mosaic lets it
        # pipeline DMAs across grid steps instead of serializing.
        compiler_params=compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=_interpret(interpret),
    )(qm, km, vm)
    out = out[:, :Tq].reshape(B, H, Tq, D).transpose(0, 2, 1, 3)
    if return_lse:
        return out, lse
    return out


def _flash_bwd_mask(qi, kj, *, causal, window, block_q, block_k, tq, tk):
    """Validity mask for one (block_q, block_k) tile: in-range rows/cols
    plus the causal triangle (and sliding window).  Padded Q rows carry a
    bogus lse (=-1e30 + log eps), so P must be forced to zero there or
    they'd pollute dK/dV."""
    q_pos = qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)
    k_pos = kj * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    mask = (q_pos < tq) & (k_pos < tk)
    if causal:
        mask = mask & (k_pos <= q_pos)
        if window is not None:
            mask = mask & (k_pos > q_pos - window)
    return mask


def _flash_block_live(qi, kj, *, causal, window, block_q, block_k):
    """Block-level liveness: does tile (qi, kj) contain ANY unmasked pair?
    Shared by the fwd/dq kernels (k minor) and the dkv kernel (q minor)."""
    if not causal:
        return None
    live = kj * block_k <= qi * block_q + block_q - 1
    if window is not None:
        live &= kj * block_k + block_k - 1 > qi * block_q - window
    return live


def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                         dq_ref, acc_ref, *, scale, causal, window,
                         block_q, block_k, tq, tk, n_kb):
    """Grid = (BH, n_q_blocks, n_k_blocks), k minor; dQ accumulates in
    scratch across the k sweep (two-pass recompute backward: S and P are
    rebuilt from Q/K and the saved row logsumexp, never materialized)."""
    qi, kj = pl.program_id(1), pl.program_id(2)

    @pl.when(kj == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    def _step():
        q, k_blk, v_blk, do = q_ref[0], k_ref[0], v_ref[0], do_ref[0]
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        mask = _flash_bwd_mask(qi, kj, causal=causal, window=window,
                               block_q=block_q, block_k=block_k,
                               tq=tq, tk=tk)
        # lse/delta blocks are (block_q, 1) column vectors — broadcast
        # against the (block_q, block_k) score tile directly.
        p = jnp.where(mask, jnp.exp(s - lse_ref[0]), 0.0)
        dp = jax.lax.dot_general(
            do, v_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0]) * scale
        acc_ref[:] += jax.lax.dot_general(
            ds.astype(k_blk.dtype), k_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    live = _flash_block_live(qi, kj, causal=causal, window=window,
                             block_q=block_q, block_k=block_k)
    if live is None:
        _step()
    else:
        pl.when(live)(_step)

    @pl.when(kj == n_kb - 1)
    def _finalize():
        dq_ref[0] = acc_ref[:].astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                          dk_ref, dv_ref, dk_acc, dv_acc, *, scale, causal,
                          window, block_q, block_k, tq, tk, n_qb, n_qsweep):
    """Grid = (B*H_kv, n_k_blocks, n_qsweep), q minor; dK/dV accumulate in
    scratch across the q sweep.  With GQA, n_qsweep = n_q_blocks * G: the
    minor axis enumerates (group member g, q block qi) — every q head of
    the group folds into the same kv-head accumulator."""
    kj, i = pl.program_id(1), pl.program_id(2)
    qi = i % n_qb

    @pl.when(i == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    def _step():
        q, k_blk, v_blk, do = q_ref[0], k_ref[0], v_ref[0], do_ref[0]
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        mask = _flash_bwd_mask(qi, kj, causal=causal, window=window,
                               block_q=block_q, block_k=block_k,
                               tq=tq, tk=tk)
        p = jnp.where(mask, jnp.exp(s - lse_ref[0]), 0.0)
        dv_acc[:] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(
            do, v_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0]) * scale
        dk_acc[:] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    live = _flash_block_live(qi, kj, causal=causal, window=window,
                             block_q=block_q, block_k=block_k)
    if live is None:
        _step()
    else:
        pl.when(live)(_step)

    @pl.when(i == n_qsweep - 1)
    def _finalize():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _flash_bwd(q, k, v, out, lse, g, *, causal, scale, block_q, block_k,
               interpret, window=None):
    B, Tq, H, D = q.shape
    Tk = k.shape[1]
    H_kv = k.shape[2]
    G = _gqa_groups(q, k)
    scale_ = scale if scale is not None else D ** -0.5
    block_q, block_k, tq_p, tk_p = _flash_blocks(Tq, Tk, block_q, block_k)
    n_qb, n_kb = tq_p // block_q, tk_p // block_k

    qm = _flash_layout(q, Tq, tq_p)
    km = _flash_layout(k, Tk, tk_p)
    vm = _flash_layout(v, Tk, tk_p)
    dom = _flash_layout(g, Tq, tq_p)
    om = _flash_layout(out, Tq, tq_p)
    # delta_i = rowsum(dO * O) — cheap elementwise+reduce, left to XLA;
    # shaped (BH, T, 1) to match the kernels' column-vector blocks.
    delta = jnp.sum(dom.astype(jnp.float32) * om.astype(jnp.float32),
                    axis=-1, keepdims=True)

    itp = _interpret(interpret)
    common = dict(scale=scale_, causal=causal, window=window,
                  block_q=block_q, block_k=block_k, tq=Tq, tk=Tk)
    kv_row = _kv_row_map(H, H_kv, G)
    dq = pl.pallas_call(
        functools.partial(_flash_bwd_dq_kernel, n_kb=n_kb, **common),
        grid=(B * H, n_qb, n_kb),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, D),
                         lambda b, i, j: (kv_row(b), j, 0)),
            pl.BlockSpec((1, block_k, D),
                         lambda b, i, j: (kv_row(b), j, 0)),
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((B * H, tq_p, D), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, D), jnp.float32)],
        compiler_params=compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=itp,
    )(qm, km, vm, dom, lse, delta)

    # dK/dV: grid over kv heads; the minor sweep covers (group member g,
    # q block) so all G q heads of a group fold into one accumulator.
    # q-side rows for kv row b and sweep index i: head (b % H_kv)*G + g.
    q_row = (lambda b, i: (b, i)) if G == 1 else \
        (lambda b, i: ((b // H_kv) * H + (b % H_kv) * G + i // n_qb,
                       i % n_qb))
    dk, dv = pl.pallas_call(
        functools.partial(_flash_bwd_dkv_kernel, n_qb=n_qb,
                          n_qsweep=n_qb * G, **common),
        grid=(B * H_kv, n_kb, n_qb * G),
        in_specs=[
            pl.BlockSpec((1, block_q, D),
                         lambda b, j, i: (*q_row(b, i), 0)),
            pl.BlockSpec((1, block_k, D), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_q, D),
                         lambda b, j, i: (*q_row(b, i), 0)),
            pl.BlockSpec((1, block_q, 1),
                         lambda b, j, i: (*q_row(b, i), 0)),
            pl.BlockSpec((1, block_q, 1),
                         lambda b, j, i: (*q_row(b, i), 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, D), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, j, i: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * H_kv, tk_p, D), k.dtype),
            jax.ShapeDtypeStruct((B * H_kv, tk_p, D), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, D), jnp.float32),
            pltpu.VMEM((block_k, D), jnp.float32),
        ],
        compiler_params=compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=itp,
    )(qm, km, vm, dom, lse, delta)

    def back(x, T, nh):
        return x[:, :T].reshape(B, nh, T, D).transpose(0, 2, 1, 3)

    return back(dq, Tq, H), back(dk, Tk, H_kv), back(dv, Tk, H_kv)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def flash_attention(q, k, v, causal=False, scale=None, block_q=256,
                    block_k=1024, interpret=None, window=None):
    """Blockwise-softmax attention, forward and backward as Pallas kernels.

    q: (B, Tq, H, D); k/v: (B, Tk, H_kv, D) -> (B, Tq, H, D).  H_kv may
    divide H (grouped-query attention): q heads share kv blocks via the
    BlockSpec index maps — the repeat is never materialized — and the
    dK/dV kernel folds all G = H/H_kv group members of the q sweep into
    one kv-head accumulator.  The backward is the standard two-pass
    recompute (dQ kernel + dK/dV kernel) driven by the forward's saved
    row logsumexp — memory stays one tile per operand, the full
    attention matrix is never materialized in either direction.

    ``window=W`` (requires ``causal=True``) restricts each query to keys
    in ``(q - W, q]`` — sliding-window local attention. Fully-out-of-
    window K blocks are skipped in all three kernels, so fwd+bwd cost is
    O(T·W) instead of O(T²/2)."""
    return _flash_fwd(q, k, v, causal=causal, scale=scale, block_q=block_q,
                      block_k=block_k, interpret=interpret, window=window)


def _flash_vjp_fwd(q, k, v, causal, scale, block_q, block_k, interpret,
                   window):
    out, lse = _flash_fwd(q, k, v, causal=causal, scale=scale,
                          block_q=block_q, block_k=block_k,
                          interpret=interpret, window=window,
                          return_lse=True)
    return out, (q, k, v, out, lse)


def _flash_vjp_bwd(causal, scale, block_q, block_k, interpret, window,
                   res, g):
    q, k, v, out, lse = res
    return _flash_bwd(q, k, v, out, lse, g, causal=causal, scale=scale,
                      block_q=block_q, block_k=block_k, interpret=interpret,
                      window=window)


flash_attention.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


# ---------------------------------------------------------------------------
# Paged-attention decode (fused page gather + online softmax)
# ---------------------------------------------------------------------------

def _paged_attn_kernel(ptab_ref, pos_ref, q_ref, k_ref, v_ref, o_ref,
                       m_ref, l_ref, acc_ref, *, scale, psz, hk, g,
                       window, n_ptab):
    """Grid = (B, n_ptab): row-major sweep over each slot's logical
    pages.  The page axis is minor and ``k_ref``/``v_ref`` blocks are
    addressed THROUGH the scalar-prefetched page table (``ptab_ref`` in
    SMEM drives the BlockSpec index map), so each step is a direct
    HBM→VMEM DMA of one physical pool page — the flat ``pool[ptab]``
    logical view is never materialized.  Online-softmax state (m/l/acc)
    persists in VMEM scratch across the page sweep, exactly like the
    flash kernel's k sweep."""
    b, j = pl.program_id(0), pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, -1e30)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    pos_b = pos_ref[b]

    def _step():
        q = q_ref[0].astype(jnp.float32)          # (H, Dh)
        k_pg = k_ref[0].astype(jnp.float32)       # (psz, Hk, Dh)
        v_pg = v_ref[0].astype(jnp.float32)
        d = q.shape[-1]
        qg = q.reshape(hk, g, d)
        # grouped scores against this page: (Hk, G, psz)
        s = jnp.einsum("kgd,tkd->kgt", qg, k_pg) * scale
        k_pos = j * psz + jax.lax.broadcasted_iota(
            jnp.int32, (hk, g, psz), 2)
        mask = k_pos <= pos_b
        if window is not None:
            mask = mask & (k_pos > pos_b - window)
        s = jnp.where(mask, s, -1e30)
        m = m_ref[:].reshape(hk, g, 1)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m - m_new)
        p = jnp.where(mask, jnp.exp(s - m_new), 0.0)
        m_ref[:] = m_new.reshape(hk * g, 1)
        l_ref[:] = (alpha.reshape(hk * g, 1) * l_ref[:]
                    + jnp.sum(p, axis=-1).reshape(hk * g, 1))
        acc_ref[:] = (acc_ref[:] * alpha.reshape(hk * g, 1)
                      + jnp.einsum("kgt,tkd->kgd", p,
                                   v_pg).reshape(hk * g, d))

    # pages wholly past the query position (and, with a sliding window,
    # wholly before it) contribute nothing: skip their DMA'd compute —
    # page 0 is always live (pos >= 0), so m/l never finalize empty
    live = j * psz <= pos_b
    if window is not None:
        live = live & (j * psz + psz - 1 > pos_b - window)
    pl.when(live)(_step)

    @pl.when(j == n_ptab - 1)
    def _finalize():
        o_ref[0] = (acc_ref[:]
                    / jnp.maximum(l_ref[:], 1e-30)).astype(o_ref.dtype)


def paged_attention_decode(q, k_pool, v_pool, ptab, pos, *, page_size,
                           n_kv_heads, scale=None, window=None,
                           interpret=None):
    """One-position paged-attention decode: softmax(q·Kᵀ)·V where K/V
    live in a flat page pool and each batch row's logical pages are
    named by its page-table row.

    q: (B, H, Dh) query at each row's own position ``pos`` (B,) int32
    (RoPE already applied); k_pool/v_pool: (rows, page_size, Hk, Dh);
    ptab: (B, n_ptab) int32 physical page per logical page (rows beyond
    a slot's span point at the scratch page — masked off by ``pos``).
    Returns the (B, H, Dh) float32 attention context (pre output
    projection — the shared `_attn_scores` tail in runtime/generate.py
    applies wo/residual so layouts cannot drift).

    This is the fused half of the paged-KV design (docs/serving.md):
    the baseline gathers ``pool[ptab]`` into a (B, l_max, Hk, Dh)
    transient before the attention math; here the page table rides SMEM
    (scalar prefetch) and pages stream HBM→VMEM block-by-block through
    the BlockSpec index map, with online softmax across the sweep —
    numerics therefore differ by summation order (bounded error, pinned
    in tests/test_pallas.py), never bitwise.  Reference idiom: the
    jax.experimental paged_attention TPU kernel (one DMA per
    non-contiguous page, scalar-prefetched page indices)."""
    B, H, Dh = q.shape
    rows, psz, Hk, _ = k_pool.shape
    if psz != page_size:
        raise ValueError(f"pool page size {psz} != page_size {page_size}")
    if n_kv_heads != Hk:
        raise ValueError(f"pool holds {Hk} kv heads, caller declared "
                         f"{n_kv_heads}")
    G = check_gqa_heads(H, Hk)
    n_ptab = ptab.shape[1]
    window = check_attention_window(window, True)
    scale_ = scale if scale is not None else Dh ** -0.5
    kernel = functools.partial(
        _paged_attn_kernel, scale=scale_, psz=psz, hk=Hk, g=G,
        window=window, n_ptab=n_ptab)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, n_ptab),
        in_specs=[
            pl.BlockSpec((1, H, Dh), lambda b, j, ptab, pos: (b, 0, 0)),
            pl.BlockSpec((1, psz, Hk, Dh),
                         lambda b, j, ptab, pos: (ptab[b, j], 0, 0, 0)),
            pl.BlockSpec((1, psz, Hk, Dh),
                         lambda b, j, ptab, pos: (ptab[b, j], 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, H, Dh),
                               lambda b, j, ptab, pos: (b, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((H, 1), jnp.float32),
            pltpu.VMEM((H, 1), jnp.float32),
            pltpu.VMEM((H, Dh), jnp.float32),
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, H, Dh), jnp.float32),
        # batch rows are independent; the page sweep carries the
        # online-softmax state
        compiler_params=compiler_params(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=_interpret(interpret),
    )(jnp.asarray(ptab, jnp.int32), jnp.asarray(pos, jnp.int32),
      q, k_pool, v_pool)


# ---------------------------------------------------------------------------
# Fused dropout with in-kernel counter-based RNG
# ---------------------------------------------------------------------------

import numpy as np  # noqa: E402  (np scalars stay literals under tracing)

_GOLDEN = np.uint32(0x9E3779B9)
_MIX1 = np.uint32(0x85EBCA6B)
_MIX2 = np.uint32(0xC2B2AE35)


def _splitmix32(z):
    z = (z + _GOLDEN).astype(jnp.uint32)
    z = (z ^ (z >> 16)) * _MIX1
    z = (z ^ (z >> 13)) * _MIX2
    return z ^ (z >> 16)


def _dropout_kernel(seed_ref, x_ref, o_ref, *, rate, block_rows, block_cols,
                    n_cols):
    # The mask bit for element (row, col) is a hash of its GLOBAL linear
    # index, so the mask is identical for any (block_rows, block_cols)
    # tiling — backward can regenerate it with different tile choices.
    pid_r, pid_c = pl.program_id(0), pl.program_id(1)
    r = jax.lax.broadcasted_iota(jnp.uint32, (block_rows, block_cols), 0)
    c = jax.lax.broadcasted_iota(jnp.uint32, (block_rows, block_cols), 1)
    row = pid_r.astype(jnp.uint32) * np.uint32(block_rows) + r
    col = pid_c.astype(jnp.uint32) * np.uint32(block_cols) + c
    lin = row * np.uint32(n_cols) + col
    # One fmix32-style finalizer pass (add-xorshift-mul x2) is already a
    # full-avalanche mixer for counter inputs; u32 multiplies are the
    # VPU's slow op, and a second pass measurably lost to XLA's threefry
    # on-chip (bench_tpu).  Seed is pre-whitened so consecutive seeds
    # don't produce correlated streams.
    bits = _splitmix32(lin ^ _splitmix32(seed_ref[0, 0]))
    # top 24 bits -> uniform in [0, 1); Mosaic lacks uint32->f32 casts, so
    # bitcast the (always-positive) value through int32 first.
    u = jax.lax.bitcast_convert_type(
        bits >> 8, jnp.int32).astype(jnp.float32) * (1.0 / 16777216.0)
    keep = (u >= rate).astype(jnp.float32) / (1.0 - rate)
    o_ref[:] = (x_ref[:].astype(jnp.float32) * keep).astype(o_ref.dtype)


# Per-block element budget: a few f32 buffers per block must fit VMEM
# (~16 MB) with headroom for Mosaic's stack.
_DROPOUT_BLOCK_ELEMS = 1 << 19


def _dropout_apply(x, seed, rate, block_rows, interpret):
    orig_shape = x.shape
    flat = x.reshape(-1, orig_shape[-1]) if x.ndim > 1 else x.reshape(1, -1)
    rows, cols = flat.shape
    if cols <= 8192:
        block_cols = _round_up(cols, 128)
    else:
        # Near-equal 128-aligned column blocks keep padding under one lane
        # width (a flat 8192 cap would pad e.g. 8320 cols to 16384 —
        # nearly doubling hashed+written elements).
        n_cb = -(-cols // 8192)
        block_cols = _round_up(-(-cols // n_cb), 128)
    block_rows = max(8, min(block_rows, rows,
                            _DROPOUT_BLOCK_ELEMS // block_cols))
    rows_p = _round_up(rows, block_rows)
    cols_p = _round_up(cols, block_cols)
    flat = jnp.pad(flat, ((0, rows_p - rows), (0, cols_p - cols)))
    seed_arr = jnp.asarray(seed, jnp.uint32).reshape(1, 1)
    kernel = functools.partial(_dropout_kernel, rate=float(rate),
                               block_rows=block_rows,
                               block_cols=block_cols, n_cols=cols)
    out = pl.pallas_call(
        kernel,
        grid=(rows_p // block_rows, cols_p // block_cols),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((block_rows, block_cols), lambda i, j: (i, j)),
        ],
        out_specs=pl.BlockSpec((block_rows, block_cols),
                               lambda i, j: (i, j)),
        out_shape=jax.ShapeDtypeStruct((rows_p, cols_p), x.dtype),
        interpret=_interpret(interpret),
    )(seed_arr, flat)
    return out[:rows, :cols].reshape(orig_shape)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
def fused_dropout(x, seed, rate, block_rows=256, interpret=None):
    """Dropout whose mask is a deterministic splitmix32 hash of
    (seed, element index), generated inside the kernel.  The backward pass
    re-runs the same kernel on the cotangent — the mask is never stored
    (reference stored the random state per unit: ocl/random.cl)."""
    return _dropout_apply(x, seed, rate, block_rows, interpret)


def _dropout_vjp_fwd(x, seed, rate, block_rows, interpret):
    return _dropout_apply(x, seed, rate, block_rows, interpret), seed


def _dropout_vjp_bwd(rate, block_rows, interpret, seed, g):
    # Same seed -> same mask -> d/dx (x * keep) = g * keep.
    return _dropout_apply(g, seed, rate, block_rows, interpret), None


fused_dropout.defvjp(_dropout_vjp_fwd, _dropout_vjp_bwd)


# ---------------------------------------------------------------------------
# Mean/dispersion normalize
# ---------------------------------------------------------------------------

def _mean_disp_kernel(x_ref, mean_ref, rdisp_ref, o_ref):
    o_ref[:] = ((x_ref[:].astype(jnp.float32) - mean_ref[:])
                * rdisp_ref[:]).astype(o_ref.dtype)


def mean_disp_normalize(x, mean, rdisp, *, block_rows=128, block_cols=4096,
                        interpret=None, dtype=jnp.float32):
    """(x - mean) * rdisp with x typically uint8; tiled elementwise kernel
    (reference: ocl/mean_disp_normalizer.cl).  Columns are tiled too so
    image-scale feature counts (e.g. 224·224·3) never exceed VMEM."""
    orig_shape = x.shape
    flat = x.reshape(orig_shape[0], -1)
    if jnp.issubdtype(flat.dtype, jnp.unsignedinteger):
        # Mosaic has no unsigned->float casts; widen outside (XLA fuses the
        # widening into the producing gather/copy).
        flat = flat.astype(jnp.int32)
    rows, cols = flat.shape
    mean_f = mean.reshape(1, -1).astype(jnp.float32)
    rdisp_f = rdisp.reshape(1, -1).astype(jnp.float32)
    block_rows = min(block_rows, rows)
    block_cols = min(block_cols, _round_up(cols, 128))
    rows_p = _round_up(rows, block_rows)
    cols_p = _round_up(cols, block_cols)
    flat = jnp.pad(flat, ((0, rows_p - rows), (0, cols_p - cols)))
    mean_f = jnp.pad(mean_f, ((0, 0), (0, cols_p - cols)))
    rdisp_f = jnp.pad(rdisp_f, ((0, 0), (0, cols_p - cols)))
    out = pl.pallas_call(
        _mean_disp_kernel,
        grid=(rows_p // block_rows, cols_p // block_cols),
        in_specs=[
            pl.BlockSpec((block_rows, block_cols), lambda i, j: (i, j)),
            pl.BlockSpec((1, block_cols), lambda i, j: (0, j)),
            pl.BlockSpec((1, block_cols), lambda i, j: (0, j)),
        ],
        out_specs=pl.BlockSpec((block_rows, block_cols),
                               lambda i, j: (i, j)),
        out_shape=jax.ShapeDtypeStruct((rows_p, cols_p), dtype),
        interpret=_interpret(interpret),
    )(flat, mean_f, rdisp_f)
    return out[:rows, :cols].reshape(orig_shape)


# ---------------------------------------------------------------------------
# Minibatch gather via scalar-prefetched indices
# ---------------------------------------------------------------------------

def _gather_kernel(idx_ref, data_ref, out_ref, sem):
    i = pl.program_id(0)
    dma = pltpu.make_async_copy(data_ref.at[idx_ref[i]], out_ref.at[i], sem)
    dma.start()
    dma.wait()


def pack_rows(data):
    """Pre-pack ``data`` (N, ...) into the (N, 8, f_p/8) tiled row layout
    ``gather_rows_packed`` DMAs from (features padded to a multiple of
    8·128 — Mosaic rejects single-row slices of a (8,128)-tiled 2-D memref,
    so the per-index DMA must slice only an untiled leading dim).  Pack once
    at dataset-upload time; gathering from the packed form then never
    touches the full dataset again (see FullBatchLoader._upload)."""
    orig_shape = data.shape
    flat = data.reshape(orig_shape[0], -1)
    n, f = flat.shape
    f_p = _round_up(f, 8 * 128)
    packed = jnp.pad(flat, ((0, 0), (0, f_p - f))).reshape(n, 8, f_p // 8)
    return packed, f, orig_shape[1:]


def unpack_rows(packed, f, sample_shape):
    m = packed.shape[0]
    return packed.reshape(m, -1)[:, :f].reshape((m,) + tuple(sample_shape))


def gather_rows_packed(packed, idx, *, interpret=None):
    """Gather pre-packed rows (see ``pack_rows``) as one direct HBM→HBM DMA
    per scalar-prefetched index."""
    m = idx.shape[0]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(m,),
        in_specs=[pl.BlockSpec(memory_space=_HBM)],
        out_specs=pl.BlockSpec(memory_space=_HBM),
        scratch_shapes=[pltpu.SemaphoreType.DMA(())],
    )
    return pl.pallas_call(
        _gather_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((m,) + packed.shape[1:],
                                       packed.dtype),
        interpret=_interpret(interpret),
        compiler_params=compiler_params(has_side_effects=True),
    )(jnp.asarray(idx, jnp.int32), packed)


def gather_rows(data, idx, *, interpret=None):
    """``data[idx]`` via per-index HBM DMA (reference:
    ocl/fullbatch_loader.cl fill_minibatch_data_labels).  Convenience
    one-shot form — packs on every call; steady-state callers should
    ``pack_rows`` once and use ``gather_rows_packed``."""
    packed, f, sample_shape = pack_rows(data)
    out = gather_rows_packed(packed, idx, interpret=interpret)
    return unpack_rows(out, f, sample_shape)
