"""Loss ops (the reference's "evaluators": softmax cross-entropy and MSE,
docs manualrst_veles_algorithms.rst:157 item 7; the Znicz EvaluatorSoftmax /
EvaluatorMSE units plugged between forwards and gradient units)."""

from __future__ import annotations

import jax
import jax.numpy as jnp


def softmax_cross_entropy(logits, labels, *, mask=None):
    """Mean CE over the batch; labels are integer class ids.

    Returns (loss, n_err) — n_err is the reference's per-minibatch error
    count that Decision accumulated into epoch error rates."""
    logits = logits.astype(jnp.float32)
    logp = jax.nn.log_softmax(logits, axis=-1)
    ce = -jnp.take_along_axis(logp, labels[..., None].astype(jnp.int32),
                              axis=-1)[..., 0]
    pred = jnp.argmax(logits, axis=-1)
    err = (pred != labels).astype(jnp.float32)
    if mask is not None:
        denom = jnp.maximum(mask.sum(), 1.0)
        return (ce * mask).sum() / denom, (err * mask).sum()
    return ce.mean(), err.sum()


def mse_loss(output, target, *, mask=None, root_flag=False):
    """Mean squared error; returns (loss, sum of per-sample sq-norm errors)
    so RMSE can be aggregated per epoch (reference AE RMSE metric,
    manualrst_veles_algorithms.rst:71)."""
    output = output.astype(jnp.float32)
    target = target.astype(jnp.float32)
    diff = output.reshape(output.shape[0], -1) - target.reshape(
        target.shape[0], -1)
    per_sample = jnp.mean(jnp.square(diff), axis=-1)
    if mask is not None:
        denom = jnp.maximum(mask.sum(), 1.0)
        loss = (per_sample * mask).sum() / denom
        agg = (per_sample * mask).sum()
    else:
        loss = per_sample.mean()
        agg = per_sample.sum()
    if root_flag:
        loss = jnp.sqrt(loss)
    return loss, agg
