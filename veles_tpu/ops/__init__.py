"""Functional op library — the XLA/Pallas replacement for the reference's
OpenCL/CUDA kernel directory (reference: ocl/*.cl, cuda/*.cu; see
SURVEY.md §2.3). Every op is a pure jnp/lax function, testable against the
numpy references in tests/.
"""

from .activations import (relu, scaled_tanh, sigmoid, sincos, log_softmax,
                          softmax, ACTIVATIONS)
from .linear import dense, smart_uniform_init
from .convolution import conv2d, deconv2d
from .pooling import (max_pool, avg_pool, max_pool_with_argmax, max_unpool,
                      avg_unpool)
from .lrn import local_response_norm
from .losses import softmax_cross_entropy, mse_loss
from .normalize import mean_disp_normalize
from .reduce import matrix_reduce
from .recurrent import gru_scan, lstm_scan, rnn_scan

_PALLAS_EXPORTS = ("flash_attention", "fused_dropout", "gather_rows",
                   "use_pallas_default")


def __getattr__(name):
    # Lazy: importing veles_tpu must not pull in the Mosaic TPU machinery
    # on hosts that never run a hand-written kernel.
    if name == "pallas_kernels" or name in _PALLAS_EXPORTS:
        import importlib
        mod = importlib.import_module(".pallas_kernels", __name__)
        globals()["pallas_kernels"] = mod  # cache; skip __getattr__ next time
        if name == "pallas_kernels":
            return mod
        return getattr(mod, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
