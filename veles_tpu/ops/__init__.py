"""Functional op library — the XLA/Pallas replacement for the reference's
OpenCL/CUDA kernel directory (reference: ocl/*.cl, cuda/*.cu; see
SURVEY.md §2.3). Every op is a pure jnp/lax function, testable against the
numpy references in tests/.
"""

from .activations import (relu, scaled_tanh, sigmoid, sincos, log_softmax,
                          rotary_embedding,
                          softmax, ACTIVATIONS)
from .linear import dense, smart_uniform_init
from .convolution import conv2d, deconv2d
from .pooling import (max_pool, avg_pool, max_pool_with_argmax, max_unpool,
                      avg_unpool)
from .lrn import local_response_norm
from .losses import softmax_cross_entropy, mse_loss
from .normalize import mean_disp_normalize
from .reduce import matrix_reduce
from .recurrent import gru_scan, lstm_scan, rnn_scan

def use_pallas_default(platform=None) -> bool:
    """Shared policy for every Pallas-vs-XLA switch in the package
    (Dropout, blockwise_attention, FullBatchLoader): compiled kernels
    engage only when the target platform is TPU.  Inside jit the committed
    device is unknowable at trace time, so callers that allow non-default
    placement must pass ``platform`` (FullBatchLoader does) or their
    explicit ``use_pallas`` flag.  Lives here — NOT in pallas_kernels — so
    evaluating the policy never imports the Mosaic machinery."""
    if platform is None:
        import jax
        platform = jax.default_backend()
    return platform == "tpu"


def check_attention_window(window, causal):
    """Shared validation for sliding-window attention (kernel, blockwise
    and ring paths): None disables; otherwise a positive int with
    causal=True (0 would silently mask everything to zeros)."""
    if window is None:
        return None
    if not causal:
        raise ValueError("sliding-window attention requires causal=True")
    window = int(window)
    if window < 1:
        raise ValueError(f"window must be >= 1, got {window} "
                         "(use window=None to disable)")
    return window


def check_gqa_heads(n_heads: int, n_kv_heads: int) -> int:
    """Shared GQA validation: returns the group size H / H_kv."""
    if n_kv_heads < 1 or n_heads % n_kv_heads:
        raise ValueError(f"q heads {n_heads} must be a positive multiple "
                         f"of kv heads {n_kv_heads}")
    return n_heads // n_kv_heads


_PALLAS_EXPORTS = ("flash_attention", "fused_dropout", "gather_rows")


def __getattr__(name):
    # Lazy: importing veles_tpu must not pull in the Mosaic TPU machinery
    # on hosts that never run a hand-written kernel.
    if name == "pallas_kernels" or name in _PALLAS_EXPORTS:
        import importlib
        mod = importlib.import_module(".pallas_kernels", __name__)
        globals()["pallas_kernels"] = mod  # cache; skip __getattr__ next time
        if name == "pallas_kernels":
            return mod
        return getattr(mod, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
