"""Functional op library — the XLA/Pallas replacement for the reference's
OpenCL/CUDA kernel directory (reference: ocl/*.cl, cuda/*.cu; see
SURVEY.md §2.3). Every op is a pure jnp/lax function, testable against the
numpy references in tests/.
"""

from .activations import (relu, scaled_tanh, sigmoid, sincos, log_softmax,
                          softmax, ACTIVATIONS)
from .linear import dense, smart_uniform_init
from .convolution import conv2d, deconv2d
from .pooling import (max_pool, avg_pool, max_pool_with_argmax, max_unpool,
                      avg_unpool)
from .lrn import local_response_norm
from .losses import softmax_cross_entropy, mse_loss
from .normalize import mean_disp_normalize
from .reduce import matrix_reduce
from .recurrent import gru_scan, lstm_scan, rnn_scan
