"""Mean/dispersion normalization op (reference:
ocl/mean_disp_normalizer.cl + veles/mean_disp_normalizer.py:50-138 —
(x - mean) * rdisp elementwise on uint8 input).

Default path is one fused jnp expression — XLA folds the cast+sub+mul into
surrounding ops, so a hand kernel buys nothing in a fused graph.  The
explicit Pallas kernel (ops/pallas_kernels.mean_disp_normalize) is the
standalone-VMEM variant for callers normalizing outside a larger jit;
``use_pallas=True`` selects it.  Changes to the math must land in BOTH.
"""

from __future__ import annotations

import jax.numpy as jnp


def mean_disp_normalize(x, mean, rdisp, dtype=jnp.float32,
                        use_pallas: bool = False):
    if use_pallas:
        from .pallas_kernels import mean_disp_normalize as _pallas_impl
        return _pallas_impl(x, mean, rdisp, dtype=dtype)
    return (x.astype(dtype) - mean.astype(dtype)) * rdisp.astype(dtype)
