"""Mean/dispersion normalization op (reference:
ocl/mean_disp_normalizer.cl + veles/mean_disp_normalizer.py:50-138 —
(x - mean) * rdisp elementwise on uint8 input). One fused jnp expression on
TPU; XLA folds the cast+sub+mul into surrounding ops."""

from __future__ import annotations

import jax.numpy as jnp


def mean_disp_normalize(x, mean, rdisp, dtype=jnp.float32):
    return (x.astype(dtype) - mean.astype(dtype)) * rdisp.astype(dtype)
