"""Pooling / unpooling ops (reference Znicz max/avg pooling + depooling,
docs manualrst_veles_algorithms.rst:31-60). ``lax.reduce_window`` lowers to
the VPU; max_unpool reconstructs from stored argmax switches the way Znicz
depooling consumed the pooling unit's output offsets.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def _pair(v):
    return (v, v) if isinstance(v, int) else tuple(v)


def max_pool(x, window=2, stride=None, padding="VALID"):
    """x: (N,H,W,C)."""
    w = _pair(window)
    s = _pair(stride) if stride is not None else w
    return jax.lax.reduce_window(
        x, -jnp.inf, jax.lax.max,
        (1, w[0], w[1], 1), (1, s[0], s[1], 1), padding)


def avg_pool(x, window=2, stride=None, padding="VALID"):
    w = _pair(window)
    s = _pair(stride) if stride is not None else w
    summed = jax.lax.reduce_window(
        x, 0.0, jax.lax.add, (1, w[0], w[1], 1), (1, s[0], s[1], 1), padding)
    if padding == "VALID":
        return summed / (w[0] * w[1])
    counts = jax.lax.reduce_window(
        jnp.ones_like(x), 0.0, jax.lax.add,
        (1, w[0], w[1], 1), (1, s[0], s[1], 1), padding)
    return summed / counts


def max_pool_with_argmax(x, window=2, stride=None):
    """Returns (pooled, one-hot switches) for later unpooling."""
    w = _pair(window)
    s = _pair(stride) if stride is not None else w
    pooled = max_pool(x, w, s)
    # Switches: 1 where the input equals the pooled value broadcast back.
    # Positions no VALID window covers (odd sizes) get -inf -> never a switch.
    up = _broadcast_back(pooled, x.shape, s, fill=-jnp.inf)
    switches = (x == up).astype(x.dtype)
    return pooled, switches


def _broadcast_back(pooled, in_shape, s, fill=0.0):
    """Upsample pooled by stride back to in_shape, padding uncovered tail."""
    y = jnp.repeat(jnp.repeat(pooled, s[0], axis=1), s[1], axis=2)
    y = y[:, :in_shape[1], :in_shape[2], :]
    pad_h = in_shape[1] - y.shape[1]
    pad_w = in_shape[2] - y.shape[2]
    if pad_h or pad_w:
        y = jnp.pad(y, ((0, 0), (0, pad_h), (0, pad_w), (0, 0)),
                    constant_values=fill)
    return y


def max_unpool(pooled, switches, window=2):
    """Depool using stored switches (Znicz depooling parity)."""
    w = _pair(window)
    up = _broadcast_back(pooled, switches.shape, w)
    return up * switches


def avg_unpool(pooled, window=2, out_hw=None):
    w = _pair(window)
    up = jnp.repeat(jnp.repeat(pooled, w[0], axis=1), w[1], axis=2)
    up = up / (w[0] * w[1])
    if out_hw is not None:
        up = up[:, :out_hw[0], :out_hw[1], :]
    return up
