"""Dense (all2all) op + weight init.

Replaces the reference's tiled OpenCL gemm (reference:
ocl/matrix_multiplication.cl + ocl/gemm.cl — block-tiled, float4-vectorized,
3 summation precision levels selected by PRECISION_LEVEL; CUDA path used
cuBLAS, veles/backends.py:829-836). On TPU the MXU is driven through
``jnp.dot``/``lax.dot_general``; the precision-level knob maps onto
``jax.lax.Precision`` + float32 accumulation over bfloat16 inputs, which is
what the Kahan/multi-partial kernels were approximating on GPUs.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

_PRECISION_LEVELS = {0: jax.lax.Precision.DEFAULT,
                     1: jax.lax.Precision.HIGH,
                     2: jax.lax.Precision.HIGHEST}


def config_precision():
    """Map the reference's summation PRECISION_LEVEL (0 = fast, 1 = Kahan,
    2 = multi-partial; ocl/matrix_multiplication.cl, selected via
    root.common.precision config) onto lax.Precision for every matmul/conv
    in the package. On TPU: 0 = bf16 MXU passes, 1/2 = extra passes for
    f32-grade accumulation."""
    from ..config import root
    level = getattr(root.common, "precision_level", 0)
    return _PRECISION_LEVELS.get(int(level), jax.lax.Precision.DEFAULT)


def dense(x, w, b=None, *, precision=None, compute_dtype=None):
    """y = x @ w + b with f32 accumulation.

    x: (batch, in), w: (in, out). If ``compute_dtype`` is set (bf16 policy),
    inputs are cast down but accumulation stays float32
    (``preferred_element_type``), matching PRECISION_LEVEL>0 semantics of the
    reference kernels without a custom kernel.
    """
    out_dtype = jnp.result_type(x.dtype, w.dtype)
    if compute_dtype is not None:
        x = x.astype(compute_dtype)
        w = w.astype(compute_dtype)
    y = jax.lax.dot_general(
        x, w, (((x.ndim - 1,), (0,)), ((), ())),
        precision=config_precision() if precision is None else precision,
        preferred_element_type=jnp.float32)
    y = y.astype(out_dtype)
    if b is not None:
        y = y + b
    return y


def smart_uniform_init(key, shape, fan_in=None, dtype=jnp.float32, scale=1.0):
    """Znicz "smart weight init" (reference: docs
    manualrst_veles_algorithms.rst:165 item 12): uniform in
    ±scale/sqrt(fan_in) — i.e. LeCun-style scaling."""
    if fan_in is None:
        fan_in = int(np.prod(shape[:-1])) if len(shape) > 1 else shape[0]
    limit = scale / np.sqrt(max(fan_in, 1))
    return jax.random.uniform(key, shape, dtype, -limit, limit)
