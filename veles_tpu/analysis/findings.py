"""Finding model: what every rule emits and what the baseline stores.

A finding is identified across edits by its *fingerprint* — rule id,
repo-relative path, enclosing symbol, and the whitespace-normalized
source line — NOT by line number, so a baseline survives unrelated
edits above the finding but goes stale the moment the flagged line
itself changes (which is exactly when it deserves a fresh look).
"""

from __future__ import annotations

import dataclasses
import hashlib

#: severity per rule id (docs/analysis.md has the full catalogue).
SEVERITIES = {
    "VA001": "warning",   # suppression without a reason
    "VA002": "warning",   # stale baseline entry (finding/file gone)
    "VA003": "error",     # unparseable source
    "VT101": "error",     # Python control flow on a traced value
    "VT102": "error",     # host coercion of a traced value
    "VT103": "warning",   # host-effect call inside traced scope
    "VT104": "warning",   # unordered iteration feeding trace order
    "VC201": "error",     # guarded field touched outside its lock
    "VC202": "error",     # bare acquire() without try/finally release
    "VC203": "error",     # guarded-by names a lock the class never defines
    "VK301": "error",     # root.common.* read with no declared default
    "VK302": "warning",   # declared config key nobody reads
    "VK303": "warning",   # declared config key absent from the docs
    "VM401": "error",     # metric registered but absent from the docs
    "VM402": "warning",   # metric documented but registered nowhere
    "VS501": "error",     # collective/spec axis no mesh declares
    "VS502": "error",     # collective outside shard_map/schedule scope
    "VS503": "error",     # partition spec references undeclared axis
    "VP601": "error",     # per-call-varying value into a builder slot
    "VP602": "warning",   # mapping-order pytree structure in a builder
    "VP603": "error",     # builder on a hot path outside StepCache
    "VC204": "error",     # lock-order cycle (deadlock)
    "VC205": "error",     # blocking call under an annotated lock
    "VR701": "error",     # resource acquired, not released on an exit path
    "VR702": "error",     # non-daemon thread with no join on any shutdown path
    "VR703": "warning",   # file/socket handle outside with/try-finally
    "VR704": "error",     # durable write skipping tmp-fsync-rename
}

#: rule families for the CLI's per-family counts (--json): prefix ->
#: catalogue family id.  Stable key set — CI dashboards chart these.
FAMILIES = ("VA0xx", "VT1xx", "VC2xx", "VK3xx", "VM4xx", "VS5xx",
            "VP6xx", "VR7xx")


def family(rule: str) -> str:
    """``VT101`` -> ``VT1xx``."""
    return rule[:3] + "xx"


@dataclasses.dataclass
class Finding:
    rule: str
    path: str           # repo-relative, posix separators
    line: int           # 1-based
    col: int            # 0-based
    message: str
    hint: str = ""
    symbol: str = ""    # enclosing ``Class.method`` / function, if any
    snippet: str = ""   # stripped source line the finding anchors to

    @property
    def severity(self) -> str:
        return SEVERITIES.get(self.rule, "error")

    def fingerprint(self) -> str:
        norm = " ".join(self.snippet.split())
        raw = "|".join((self.rule, self.path, self.symbol, norm))
        return hashlib.sha256(raw.encode()).hexdigest()[:16]

    def to_dict(self) -> dict:
        return {"rule": self.rule, "severity": self.severity,
                "path": self.path, "line": self.line, "col": self.col,
                "symbol": self.symbol, "message": self.message,
                "hint": self.hint, "snippet": self.snippet,
                "fingerprint": self.fingerprint()}

    def format(self) -> str:
        where = f"{self.path}:{self.line}:{self.col + 1}"
        sym = f" [{self.symbol}]" if self.symbol else ""
        hint = f"\n    fix: {self.hint}" if self.hint else ""
        return (f"{where}: {self.rule} {self.severity}: "
                f"{self.message}{sym}{hint}")


def sort_key(f: Finding):
    return (f.path, f.line, f.col, f.rule)
