"""VM4xx — metric-name drift between code and docs/observability.md.

The metrics registry (runtime/metrics.py) is string-keyed and
registration is idempotent, which is ergonomic and treacherous in
exactly the way the auto-vivifying config tree is (VK3xx): a renamed
metric silently starts a second series, dashboards and the bench
scraper keep reading the dead name, and nothing fails.  This rule
cross-references two sources of truth:

* **registrations** — every statically visible
  ``.counter("vt_*", ...)`` / ``.gauge(...)`` / ``.histogram(...)``
  call with a literal ``vt_``-prefixed name (the metric namespace; the
  prefix is what separates a metric registration from any other
  ``counter()`` call);
* **docs** — ``vt_*`` names mentioned in ``docs/observability.md``
  (the "Metrics & tracing" reference table).

VM401  a metric registered in code but absent from
       docs/observability.md — the reference table is the scrape
       contract; an undocumented series is invisible to operators —
       error.
VM402  a metric documented but registered nowhere — a dashboard
       pointed at it scrapes zeros forever — warning.  Derived
       histogram series (``_bucket``/``_sum``/``_count`` suffixes of a
       registered name) are exempt.  "Nowhere" needs the full
       registration inventory, which a single-file lint run does not
       have — so VM402 only fires on package-directory scans (an
       ``__init__.py`` in the scanned set) that register at least one
       metric, the way VK302 bails when config.py is not in the scan.

Both checks no-op when ``docs/observability.md`` is absent (fixture
trees), mirroring VK303's missing-docs behavior.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Optional, Tuple

from .findings import Finding
from .pysrc import ParsedFile

#: the metric namespace: only literal names with this prefix count as
#: registrations (an unrelated ``.counter()`` API elsewhere must not).
METRIC_PREFIX = "vt_"

_REGISTER_METHODS = ("counter", "gauge", "histogram")
_NAME_RE = re.compile(r"\bvt_[a-z0-9_]+\b")
_DERIVED_SUFFIXES = ("_bucket", "_sum", "_count")

DOC_FILE = "observability.md"


def _symbol_at(pf: ParsedFile, line: int) -> str:
    best, best_span = "", None
    for q, info in pf.functions.items():
        node = info.node
        end = getattr(node, "end_lineno", node.lineno)
        if node.lineno <= line <= end:
            span = end - node.lineno
            if best_span is None or span < best_span:
                best, best_span = q, span
    return best


def _collect_registrations(pf: ParsedFile) -> Dict[str, Tuple[int, int]]:
    """name -> (line, col) of the first registration call in the file."""
    out: Dict[str, Tuple[int, int]] = {}
    if METRIC_PREFIX not in pf.source:   # cheap textual pre-filter
        return out
    for node in ast.walk(pf.tree):
        if not isinstance(node, ast.Call) or not node.args:
            continue
        fn = node.func
        method = fn.attr if isinstance(fn, ast.Attribute) \
            else fn.id if isinstance(fn, ast.Name) else None
        if method not in _REGISTER_METHODS:
            continue
        arg = node.args[0]
        if not (isinstance(arg, ast.Constant)
                and isinstance(arg.value, str)):
            continue
        name = arg.value
        if not name.startswith(METRIC_PREFIX):
            continue
        out.setdefault(name, (node.lineno, node.col_offset))
    return out


def _doc_names(docs_dir: str) -> Optional[Tuple[str, str]]:
    path = os.path.join(docs_dir, DOC_FILE)
    if not os.path.isfile(path):
        return None
    try:
        with open(path, encoding="utf-8") as f:
            return path, f.read()
    except OSError:
        return None


def check(files: List[ParsedFile],
          docs_dir: Optional[str] = None, *,
          package_scan: Optional[bool] = None) -> List[Finding]:
    if not docs_dir:
        return []
    doc = _doc_names(docs_dir)
    if doc is None:
        return []
    doc_path, doc_text = doc
    documented = set(_NAME_RE.findall(doc_text))
    doc_lines = doc_text.splitlines()

    def _doc_line(name: str) -> int:
        for i, line in enumerate(doc_lines, 1):
            if name in line:
                return i
        return 1

    registered: Dict[str, Tuple[ParsedFile, int, int]] = {}
    for pf in files:
        for name, (line, col) in _collect_registrations(pf).items():
            registered.setdefault(name, (pf, line, col))

    out: List[Finding] = []
    for name in sorted(registered):
        if name in documented:
            continue
        pf, line, col = registered[name]
        out.append(Finding(
            rule="VM401", path=pf.relpath, line=line, col=col,
            message=f"metric `{name}` is registered here but never "
                    f"mentioned in docs/{DOC_FILE} — the reference "
                    "table is the scrape contract",
            hint=f"add `{name}` to the docs/{DOC_FILE} metric table",
            symbol=_symbol_at(pf, line),
            snippet=pf.line_text(line)))

    def _is_derived(name: str) -> bool:
        for suf in _DERIVED_SUFFIXES:
            if name.endswith(suf) and name[:-len(suf)] in registered:
                return True
        return False

    # "registered nowhere" is only provable against the full inventory:
    # skip VM402 for subset scans — the engine says whether a package
    # DIRECTORY was analyzed (an __init__.py merely being among the
    # changed files proves nothing); legacy callers (None) fall back to
    # the scanned-files inference — and for trees registering nothing
    if package_scan is None:
        package_scan = any(
            os.path.basename(pf.relpath) == "__init__.py"
            for pf in files)
    if registered and package_scan:
        for name in sorted(documented):
            if name in registered or _is_derived(name):
                continue
            out.append(Finding(
                rule="VM402",
                path=os.path.basename(os.path.dirname(doc_path))
                + "/" + DOC_FILE,
                line=_doc_line(name), col=0,
                message=f"metric `{name}` is documented in "
                        f"docs/{DOC_FILE} but registered nowhere — a "
                        "dashboard pointed at it scrapes zeros forever",
                hint="delete the table row or fix the name to match "
                     "the registration",
                symbol="", snippet=name))
    return out
