"""Trace-root registry: where traced programs begin.

The trace-safety rules (VT1xx) only fire inside *traced scope* —
functions whose bodies become XLA programs.  That set is declared here,
per module, and closed by the analyzer over the **whole-package call
graph** (analysis/callgraph.py): nested ``def``s inside a root and
functions a root calls — across module boundaries, through
``from x import y``, module-attribute chains and ``self.m()``
inheritance/override dispatch — are traced too.

Two root modes:

``BUILDER``
    A program *factory* (``make_decode_fn``, ``generate``,
    ``Workflow._build_step`` …): its body runs at trace/build time — so
    host-effect calls (VT103) still matter there — but its own
    parameters are static Python (plans, unit objects, config knobs),
    not tracers.  The jitted functions it defines inside ARE traced and
    get tracer-tainted parameters automatically.

``TRACED``
    A function whose positional parameters are themselves traced values
    (``DecodePlan.step``, ``sample_logits``, ``_attn_decode_step`` …).
    Keyword-only parameters stay untainted — in this codebase they are
    static sampling/config knobs by convention.

Extending for a new program kind: add its builder/step qualnames to
the module entry below — nothing else; the package-wide call-graph
closure picks up everything they call (the speculative-decode
``make_verify_fn`` below landed exactly this way).  docs/analysis.md
walks through the workflow.
"""

from __future__ import annotations

BUILDER = "builder"
TRACED = "traced"

#: module path (relative to the ``veles_tpu`` package, posix slashes)
#: -> {qualname: mode}.  Qualnames are ``func`` or ``Class.method``.
TRACE_ROOTS = {
    "runtime/generate.py": {
        "_attn_cache_init": BUILDER,
        "_rec_state_init": BUILDER,
        "_rec_decode_step": TRACED,
        "_rope_rows": TRACED,
        "_attn_decode_step": TRACED,
        "_attn_scores": TRACED,
        "DecodePlan.init_caches": BUILDER,
        "DecodePlan.step": TRACED,
        "sample_logits": TRACED,
        "generate": BUILDER,
        "generate_beam": BUILDER,
    },
    "runtime/engine.py": {
        "make_decode_fn": BUILDER,
        "make_prefill_fn": BUILDER,
        "_make_paged_prefill_fn": BUILDER,
        "make_verify_fn": BUILDER,      # speculative verify program
        #                                 (the third program kind)
        "make_megastep_fn": BUILDER,    # fused N-micro-step decode
        #                                 (the fourth program kind)
        "_sample_slots": TRACED,
    },
    # step_cache.py compiles programs other modules build; it never
    # traces model math itself, so it contributes no roots — listed so
    # the next reader knows that was a decision, not an omission.
    "runtime/step_cache.py": {},
    "units/workflow.py": {
        "Workflow.forward": TRACED,
        "Workflow._metrics": TRACED,
        "Workflow._build_step": BUILDER,
        "Workflow.make_eval_step": BUILDER,
        "Workflow.make_predict_step": BUILDER,
    },
    "parallel/pipeline_compile.py": {
        "PipelinePlan._apply_acc": TRACED,
        "PipelinePlan.stage_fns": BUILDER,
        "PipelinePlan.stage_fn_shared": BUILDER,
        "PipelinePlan.loss_fn": BUILDER,
        "build_pipeline_step": BUILDER,
    },
    "export/compiled.py": {
        "_export_one": BUILDER,
    },
}

#: module path -> {qualname: (axis, axis, ...)}: functions whose bodies
#: run under ``shard_map`` (or a schedule's manual-axes scope) with the
#: listed mesh axes bound.  The VS5xx rules (sharding_rules.py) close
#: these over the package graph exactly like TRACE_ROOTS: raw collectives
#: (``psum``/``ppermute``/``all_to_all``/…) are legal only inside this
#: closure (VS502), and literal axis names used inside it must be in
#: the root's axis environment (VS501).  One-off modules mark roots
#: inline with ``# shard-map-root: axis[,axis]`` on the ``def`` line.
SHARD_MAP_ROOTS = {
    "parallel/ring_attention.py": {
        "_ring_attention_local": ("seq",),
    },
    "parallel/moe.py": {
        # the expert-parallel formulation for code ALREADY inside a
        # schedule shard_map (Context.manual_axes routes here)
        "moe_apply_manual": ("expert",),
    },
    "parallel/pipeline.py": {
        # per-shard schedule bodies: pipeline ring + batch/width axes
        "_pipeline_local": ("pipe", "data", "fsdp", "seq", "expert"),
        "_1f1b_local": ("pipe", "data", "fsdp", "seq", "expert"),
        "_interleaved_local": ("pipe", "data", "fsdp", "seq", "expert"),
    },
    "parallel/pipeline_compile.py": {
        # stage/loss closures execute inside the schedule's shard_map
        # (PipelinePlan.stage_fns docstring: Context.manual_axes)
        "PipelinePlan.stage_fns": ("pipe", "seq", "expert"),
        "PipelinePlan.stage_fn_shared": ("pipe", "seq", "expert"),
        "PipelinePlan.loss_fn": ("pipe", "seq", "expert"),
    },
    "units/parallel_nn.py": {
        # unit apply bodies run INSIDE the schedule's shard_map when
        # Context.manual_axes routes them to the manual formulations
        # (ctx.collective_mode == "manual"); their raw collectives are
        # gated on exactly that mode
        "MultiHeadAttention.apply": ("seq",),
        "MoEFFN.apply": ("expert",),
    },
}

#: ``jax.lax`` collective ops that need a named-axis binding -> 0-based
#: index of their axis-name argument (the VS5xx op inventory).
COLLECTIVE_OPS = {
    "psum": 1, "pmean": 1, "pmax": 1, "pmin": 1, "all_gather": 1,
    "ppermute": 1, "all_to_all": 1, "psum_scatter": 1, "pshuffle": 1,
    "axis_index": 0,
}

#: module path -> qualnames of host hot loops (scheduler ticks, REST
#: request handlers): traced-program *builders* reachable from these —
#: across modules and subclass overrides (ArtifactRunner hooks) —
#: must route through StepCache (recompile_rules.py, VP603) — a lazy
#: builder call here re-traces per request and smuggles the compile
#: past the flat-counter contract.  Fixture syntax:
#: ``# host-loop-root:`` on the ``def`` line.
HOST_LOOP_ROOTS = {
    "runtime/engine.py": ("DecodeEngine._loop",),
    "runtime/restful.py": ("RestfulServer.decode", "RestfulServer.infer"),
    # the fleet router's host loops (runtime/fleet.py): the scrape/
    # health thread, the per-request dispatch path, and the rolling-
    # drain cycle.  The router is pure control plane — it must never
    # reach a traced-program builder; declaring its loops here makes
    # that an enforced property, not an assumption.
    "runtime/fleet.py": ("FleetRouter._scrape_loop",
                         "FleetRouter.handle_generate",
                         "FleetRouter.handle_generate_stream",
                         "FleetRouter.rolling_drain"),
    # the batch job manager (runtime/jobs.py): dispatch workers and the
    # REST glue are pure control plane — bodies in, committed result
    # files out; they must never reach a traced-program builder.
    "runtime/jobs.py": ("JobManager._worker", "handle_jobs_request"),
}

#: builders that own a documented per-geometry compile memo instead of
#: routing through StepCache: ``generate``/``generate_beam`` keep an
#: LRU keyed on (workflow, geometry, sampling mode) in
#: runtime/generate.py (``_runner_cache``), sized by
#: ``root.common.serve.runner_cache``.  VP603 accepts these routes;
#: adding a name here is a declaration that the builder memoizes —
#: tests/test_analysis.py guards the declared set.
SELF_CACHING_BUILDERS = frozenset({"generate", "generate_beam"})

#: ``root.common`` subtrees that are deliberately NOT declared in
#: config.py: the fault-injection switchboard keeps ``root.common
#: .faults`` an empty node in production so its presence check stays one
#: falsy read (runtime/faults.py).  VK301 skips keys under these.
DYNAMIC_CONFIG_PREFIXES = ("faults",)

#: resource lifecycles the VR701 pairing rule checks over the package
#: call graph.  Per resource: the functions that *acquire* it (take
#: pages out of ``_page_free`` / bump ``_page_ref``), the functions
#: that *release* it, and the **exit roots** — every failure/retire
#: path that must provably reach a release (retire, mid-flight
#: deadline, fail-all on scheduler crash).  Qualnames per module path,
#: the TRACE_ROOTS convention; fixtures use ``# resource-acquire:`` /
#: ``# resource-release:`` def-line comments instead.
#: tests/test_analysis.py guards that the declared sets stay honest
#: (every qualname resolves and actually touches the pool fields).
RESOURCE_PAIRS = {
    "kv-pages": {
        "acquire": {"runtime/engine.py": (
            "DecodeEngine._reserve_pages",
            "DecodeEngine._alloc_page_locked")},
        "release": {"runtime/engine.py": (
            "DecodeEngine._release_slot_pages",
            "DecodeEngine._invalidate_prefix_cache")},
        "exit_roots": {"runtime/engine.py": (
            "DecodeEngine._retire",
            "DecodeEngine._post_step",      # mid-flight deadline sweep
            "DecodeEngine._fail_all",       # scheduler crash / stop
            "DecodeEngine._preempt",        # retire-and-requeue: the
            #                                 victim's pages must release
            #                                 before the winner reserves
            "DecodeEngine._advance_prefills")},  # mid-PREFILL deadline
        #                                          sweep (chunking slots
        #                                          are neither queued
        #                                          nor active)
    },
    # The fleet router's per-replica pending-dispatch ledger
    # (runtime/fleet.py): every forwarded /generate registers in the
    # chosen replica's pending set before the HTTP exchange and MUST
    # unregister on every exit — the rolling drain waits on exactly
    # this count, so a leaked entry wedges the drain forever.  The
    # ejection path is the declared exit root: ejecting a crashed
    # replica must provably empty its ledger (the dispatch threads
    # holding entries observe the failure on their own connections and
    # resubmit to survivors; their finally-release is idempotent).
    "fleet-dispatch": {
        "acquire": {"runtime/fleet.py": (
            "FleetRouter._begin_dispatch",)},
        "release": {"runtime/fleet.py": (
            "FleetRouter._end_dispatch",
            "FleetRouter._end_dispatch_locked")},
        "exit_roots": {"runtime/fleet.py": (
            "FleetRouter._eject_locked",)},
    },
    # KV-page import (runtime/engine.py, docs/serving.md
    # "Disaggregated prefill/decode"): applying a peer's serialized
    # prefix pages claims pool pages (refcount 1 via
    # ``_claim_import_page``) that MUST either register in the prefix
    # index (``_register_import_page`` drops the refcount to the
    # cached/evictable 0 state) or return to ``_page_free`` on an
    # aborted apply (``_abort_import_page``) — a claimed-but-orphaned
    # page would shrink the pool forever.  The scheduler-side apply
    # loop is the exit root: every abort path there must provably
    # reach the release.
    "kv-transfer": {
        "acquire": {"runtime/engine.py": (
            "DecodeEngine._claim_import_page",)},
        "release": {"runtime/engine.py": (
            "DecodeEngine._abort_import_page",
            "DecodeEngine._register_import_page")},
        "exit_roots": {"runtime/engine.py": (
            "DecodeEngine._apply_kv_imports",)},
    },
    # Streaming token handles (runtime/engine.py, docs/serving.md
    # "Streaming and mid-stream failover"): every ``submit(stream=
    # True)`` registers a ``_StreamHandle`` in ``_streams`` before the
    # request enters the queue, and EVERY terminal edge — retire, EOS,
    # stop-sequence, mid-flight deadline, shed, scheduler crash — must
    # provably close it, or the consumer blocks forever on a stream
    # whose request already died.  ``_observe_finish`` funnels every
    # outcome through the release, so the exit roots are the same
    # failure sweeps the kv-pages pair declares.
    "stream-handles": {
        "acquire": {"runtime/engine.py": (
            "DecodeEngine._acquire_stream",)},
        "release": {"runtime/engine.py": (
            "DecodeEngine._release_stream",)},
        "exit_roots": {"runtime/engine.py": (
            "DecodeEngine._retire",
            "DecodeEngine._post_step",
            "DecodeEngine._fail_all",
            "DecodeEngine._expire_queue",
            "DecodeEngine._advance_prefills")},
    },
    # The batch job manager's in-flight ledger (runtime/jobs.py):
    # every dispatched prompt registers in ``_inflight`` before its
    # HTTP exchange and MUST unregister on result, permanent failure,
    # cancel and shutdown — a leaked entry overstates
    # vt_job_prompts_inflight and wedges the cancel path's accounting.
    # The cancel and stop paths are the exit roots: both must provably
    # reach the release.
    "job-slots": {
        "acquire": {"runtime/jobs.py": (
            "JobManager._acquire_job_slot",)},
        "release": {"runtime/jobs.py": (
            "JobManager._release_job_slot",
            "JobManager._release_job_slot_locked")},
        "exit_roots": {"runtime/jobs.py": (
            "JobManager.cancel", "JobManager.stop")},
    },
    # The experiment manager's claimed-trial ledger
    # (experiments/manager.py, docs/experiments.md): every trial claims
    # a ``_claimed`` entry before any training work and MUST either
    # commit its durable doc (which pops the claim) or abort the claim
    # on every failure edge — a leaked entry overstates
    # vt_experiment summary inflight and marks a trial as eternally
    # in-progress for successor processes.  Cancel and drain are the
    # exit roots: both must provably sweep the ledger.
    "experiment-trials": {
        "acquire": {"experiments/manager.py": (
            "ExperimentManager._claim_trial",)},
        "release": {"experiments/manager.py": (
            "ExperimentManager._commit_trial",
            "ExperimentManager._abort_trial")},
        "exit_roots": {"experiments/manager.py": (
            "ExperimentManager.cancel",
            "ExperimentManager.stop")},
    },
}

#: modules whose file writes are durability-critical (sealed artifacts,
#: snapshots): VR704 requires the established tmp-fsync-rename idiom —
#: a plain ``open(path, "w")`` here can leave a half-written artifact
#: that a reader trusts.  Fixture syntax: ``# durable-write:`` on the
#: ``def`` line marks one function outside these modules.
DURABLE_WRITE_MODULES = (
    "experiments/store.py",
    "export/compiled.py",
    "export/package.py",
    "runtime/jobs.py",
    "runtime/snapshotter.py",
)

#: modules whose calls inside traced scope are host effects (VT103).
HOST_EFFECT_MODULES = (
    "time", "random", "os", "io", "pathlib", "shutil", "socket",
    "subprocess", "urllib", "requests", "sqlite3", "tempfile",
)

#: builtins that are host effects when called in traced scope (VT103).
HOST_EFFECT_BUILTINS = ("open", "input", "print")
