"""VT1xx — trace-safety rules.

Inside *traced scope* (functions reachable from the registry's trace
roots, closed over nested ``def``s and — since the whole-package
resolution layer in :mod:`~.callgraph` — calls across module
boundaries: a builder in ``runtime/engine.py`` pulling a step helper
from ``runtime/generate.py`` taints it too), the
analyzer runs a light forward taint pass: values produced by
``jax.*``/``jnp.*``/``lax.*`` calls are tracers; arithmetic, comparison,
subscripting, method calls and calls fed tainted arguments stay
tainted; attribute loads (``x.shape``, ``x.ndim``, ``u.rope``) break
taint because array metadata is static at trace time.  On that lattice:

VT101  Python ``if``/``while``/``assert``/conditional-expression whose
       test is tainted — host control flow on a traced value either
       crashes (ConcretizationError) or silently bakes one trace-time
       value into the compiled program.  ``x is None`` / ``x is not
       None`` and ``in``/``not in`` membership are exempt: tracers are
       never None and dict membership reads static keys.
VT102  ``float()``/``int()``/``bool()``/``np.asarray()``/``.item()`` on
       a tainted value — a host sync (and a constant-bake under jit).
VT103  host-effect calls in traced scope: ``time.*``, ``random.*``
       (the stdlib module — ``jax.random`` is fine), file/OS/network
       IO, ``print``/``open``/``input``.  They run at trace time, not
       per step, and bake their one observed value into the program.
VT104  iteration over an unordered collection (``set`` literal,
       ``set()``/``frozenset()`` call) that is not wrapped in
       ``sorted()`` — trace order follows iteration order, so the
       emitted program differs between processes.

The pass is deliberately a single statement-order sweep with no joins:
a best-effort linter that must hold zero false positives on the live
package (suppressions carry the reasons for the handful of idioms it
cannot see through), not a sound verifier.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from .findings import Finding
from .pysrc import FnInfo, ParsedFile, dotted_name
from .registry import HOST_EFFECT_BUILTINS, HOST_EFFECT_MODULES

#: builtins whose result is static host data even on tracer args
#: (len/shape-like structure queries), so they break taint.
#: ``set``/``frozenset`` qualify because tracers are unhashable — a
#: set can only ever hold static values (``set(state_dict)`` is the
#: static-keys idiom; ``set(traced_array)`` crashes at trace time).
_STATIC_BUILTINS = {
    "isinstance", "issubclass", "len", "getattr", "hasattr", "type",
    "repr", "str", "callable", "id", "format", "set", "frozenset",
}

_COERCIONS = {"float", "int", "bool"}
_NP_COERCIONS = {"numpy.asarray", "numpy.array", "numpy.ascontiguousarray"}

#: jax/jnp callables whose result is static host data even on tracer
#: arguments — dtype/shape structure predicates, legal in Python
#: control flow at trace time (``jnp.issubdtype(x.dtype, ...)`` is the
#: PRNG-key leaf-select idiom in ops/optimizers.py).
_STATIC_JAX = {"issubdtype", "result_type", "promote_types",
               "isdtype", "dtype", "eval_shape", "typeof"}


class _Taint:
    """Single-pass taint walk over one function body (nested defs are
    walked separately with their own parameter taint)."""

    def __init__(self, pf: ParsedFile, info: FnInfo,
                 params_tainted: bool, out: List[Finding]):
        self.pf = pf
        self.info = info
        self.out = out
        self.env: Set[str] = set()
        a = info.node.args
        if params_tainted:
            pos = list(a.posonlyargs) + list(a.args)
            # defaulted params are def-time closure bindings (`_i=_i`,
            # `states=None`): static, untainted
            n_defaults = len(a.defaults)
            tainted = pos[:len(pos) - n_defaults] if n_defaults else pos
            for arg in tainted:
                if arg.arg not in ("self", "cls"):
                    self.env.add(arg.arg)
            if a.vararg is not None:
                self.env.add(a.vararg.arg)
            # keyword-only params are static knobs by convention
            # (sampling temperature, page_size, ...): untainted.

    # -- reporting ----------------------------------------------------------
    def _emit(self, rule: str, node: ast.AST, message: str, hint: str):
        self.out.append(Finding(
            rule=rule, path=self.pf.relpath, line=node.lineno,
            col=node.col_offset, message=message, hint=hint,
            symbol=self.info.qualname,
            snippet=self.pf.line_text(node.lineno)))

    @staticmethod
    def _src(node: ast.AST, limit: int = 60) -> str:
        try:
            text = ast.unparse(node)
        except Exception:  # noqa: BLE001 — cosmetics only
            text = "<expr>"
        return text if len(text) <= limit else text[:limit - 1] + "…"

    # -- expression taint ---------------------------------------------------
    def taint(self, node: Optional[ast.AST]) -> bool:
        if node is None:
            return False
        if isinstance(node, ast.Name):
            return node.id in self.env
        if isinstance(node, ast.Attribute):
            self.taint(node.value)      # still scan for findings inside
            return False                # .shape/.ndim/.dtype are static
        if isinstance(node, ast.Subscript):
            return self.taint(node.value) | self.taint(node.slice)
        if isinstance(node, (ast.BinOp,)):
            return self.taint(node.left) | self.taint(node.right)
        if isinstance(node, ast.UnaryOp):
            return self.taint(node.operand)
        if isinstance(node, ast.BoolOp):
            return any([self.taint(v) for v in node.values])
        if isinstance(node, ast.Compare):
            operands = [node.left] + list(node.comparators)
            t = any([self.taint(v) for v in operands])
            if all(isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops) \
                    and all(isinstance(c, ast.Constant)
                            and c.value is None
                            for c in node.comparators):
                return False            # tracers are never None
            if all(isinstance(op, (ast.In, ast.NotIn)) for op in node.ops):
                return False            # dict/set membership reads keys
            return t
        if isinstance(node, ast.IfExp):
            if self.taint(node.test):
                self._flag_branch(node.test, "conditional expression")
            return self.taint(node.body) | self.taint(node.orelse)
        if isinstance(node, ast.Call):
            return self._call(node)
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            return any([self.taint(e) for e in node.elts])
        if isinstance(node, ast.Dict):
            return any([self.taint(k) for k in node.keys if k]) \
                | any([self.taint(v) for v in node.values])
        if isinstance(node, ast.Starred):
            return self.taint(node.value)
        if isinstance(node, ast.NamedExpr):
            t = self.taint(node.value)
            self._assign_name(node.target, t)
            return t
        if isinstance(node, ast.Lambda):
            sub = _Taint(self.pf, FnInfo(node, self.info.qualname,
                                         self.info.cls), False, self.out)
            sub.env = set(self.env)
            for arg in node.args.args + node.args.posonlyargs:
                sub.env.add(arg.arg)    # lambda params ride tracers
            sub.taint(node.body)
            return True                 # closure result: assume traced
        if isinstance(node, (ast.ListComp, ast.SetComp, ast.GeneratorExp,
                             ast.DictComp)):
            # the produced collection holds ELEMENT values: iterating a
            # tainted iterable yields tracer elements (the targets join
            # the env for the element expressions), but a traced
            # iterable of static projections (`{a.shape[0] for a in
            # jax.tree.leaves(p)}`) still yields a static collection —
            # the element/key/value expressions decide the result taint
            saved = set(self.env)
            for gen in node.generators:
                self._check_unordered_iter(gen.iter)
                self._assign_name(gen.target, self.taint(gen.iter))
                for cond in gen.ifs:
                    self.taint(cond)
            if isinstance(node, ast.DictComp):
                t = self.taint(node.key) | self.taint(node.value)
            else:
                t = self.taint(node.elt)
            self.env = saved
            return t
        if isinstance(node, ast.JoinedStr):
            for v in node.values:
                if isinstance(v, ast.FormattedValue):
                    self.taint(v.value)
            return False
        if isinstance(node, ast.Constant):
            return False
        # anything else: scan children, assume untainted
        for child in ast.iter_child_nodes(node):
            self.taint(child) if isinstance(child, ast.expr) else None
        return False

    def _call(self, node: ast.Call) -> bool:
        args_t = any([self.taint(a) for a in node.args]
                     + [self.taint(k.value) for k in node.keywords])
        func = node.func
        chain = dotted_name(func)
        resolved = self.pf.resolve_chain(chain) if chain else None
        # host-effect modules / builtins: VT103
        if resolved is not None:
            head = resolved.split(".")[0]
            if head in HOST_EFFECT_MODULES and "." in resolved:
                self._emit(
                    "VT103", node,
                    f"host-effect call `{self._src(func)}(...)` inside "
                    "traced scope runs once at trace time, not per step",
                    "move it out of the traced function (or pass its "
                    "result in as data)")
                return False
        if isinstance(func, ast.Name):
            if func.id in HOST_EFFECT_BUILTINS:
                self._emit(
                    "VT103", node,
                    f"host-effect call `{func.id}(...)` inside traced "
                    "scope runs once at trace time, not per step",
                    "move it out of the traced function")
                return False
            if func.id in _COERCIONS and args_t:
                self._emit(
                    "VT102", node,
                    f"`{func.id}()` forces a traced value to the host "
                    "(sync + constant-bake under jit)",
                    "keep the value traced (jnp ops / lax.cond / "
                    "jnp.where) or hoist the coercion out of traced "
                    "scope")
                return False
            if func.id in _STATIC_BUILTINS:
                return False
        if resolved in _NP_COERCIONS and args_t:
            self._emit(
                "VT102", node,
                f"`{self._src(func)}()` materializes a traced value on "
                "the host",
                "use jnp.asarray (stays traced) or hoist out of traced "
                "scope")
            return False
        if resolved is not None \
                and resolved.split(".")[0] in ("jax", "jnp") \
                and resolved.split(".")[-1] in _STATIC_JAX:
            return False            # static structure predicate
        if isinstance(func, ast.Attribute):
            recv_t = self.taint(func.value)
            if func.attr == "item" and recv_t:
                self._emit(
                    "VT102", node,
                    "`.item()` on a traced value is a host sync",
                    "keep the scalar traced, or compute it outside the "
                    "traced function")
                return False
            if resolved is not None \
                    and resolved.split(".")[0] in ("jax", "jnp"):
                return True             # tracer producer
            return recv_t or args_t     # method call on / with tracers
        if resolved is not None and resolved.split(".")[0] in ("jax",
                                                               "jnp"):
            return True
        # unknown callable: taint flows through its arguments
        return args_t

    # -- statements ---------------------------------------------------------
    def _flag_branch(self, test: ast.AST, what: str):
        self._emit(
            "VT101", test,
            f"{what} on traced value `{self._src(test)}` — host control "
            "flow inside a traced program (recompile/concretization "
            "hazard)",
            "express it as traced data flow (jnp.where / lax.cond / "
            "lax.select) or branch on static config before tracing")

    def _check_unordered_iter(self, it: ast.AST):
        unordered = isinstance(it, ast.Set)
        if isinstance(it, ast.Call) and isinstance(it.func, ast.Name) \
                and it.func.id in ("set", "frozenset"):
            unordered = True
        if unordered:
            self._emit(
                "VT104", it,
                "iteration over an unordered set feeds trace order",
                "wrap it in sorted(...) so the emitted program is "
                "deterministic across processes")

    def _assign_name(self, target: ast.AST, tainted: bool):
        if isinstance(target, ast.Name):
            (self.env.add if tainted else self.env.discard)(target.id)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._assign_name(elt, tainted)
        elif isinstance(target, ast.Starred):
            self._assign_name(target.value, tainted)
        # attribute/subscript targets: no tracked taint

    def run(self):
        self._stmts(self.info.node.body)

    def _stmts(self, body):
        for stmt in body:
            self._stmt(stmt)

    def _stmt(self, stmt: ast.stmt):
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return                      # nested defs analyzed separately
        if isinstance(stmt, ast.Assign):
            t = self.taint(stmt.value)
            for target in stmt.targets:
                self._assign_name(target, t)
        elif isinstance(stmt, ast.AugAssign):
            t = self.taint(stmt.value)
            if isinstance(stmt.target, ast.Name):
                if t or stmt.target.id in self.env:
                    self.env.add(stmt.target.id)
        elif isinstance(stmt, ast.AnnAssign):
            if stmt.value is not None:
                self._assign_name(stmt.target, self.taint(stmt.value))
        elif isinstance(stmt, ast.If):
            if self.taint(stmt.test):
                self._flag_branch(stmt.test, "`if`")
            self._stmts(stmt.body)
            self._stmts(stmt.orelse)
        elif isinstance(stmt, ast.While):
            if self.taint(stmt.test):
                self._flag_branch(stmt.test, "`while`")
            self._stmts(stmt.body)
            self._stmts(stmt.orelse)
        elif isinstance(stmt, ast.Assert):
            if self.taint(stmt.test):
                self._flag_branch(stmt.test, "`assert`")
        elif isinstance(stmt, ast.For):
            self._check_unordered_iter(stmt.iter)
            self.taint(stmt.iter)
            # loop vars stay untainted: dict iteration yields static
            # keys, and traced-array iteration unrolls statically
            self._assign_name(stmt.target, False)
            self._stmts(stmt.body)
            self._stmts(stmt.orelse)
        elif isinstance(stmt, ast.With):
            for item in stmt.items:
                self.taint(item.context_expr)
            self._stmts(stmt.body)
        elif isinstance(stmt, ast.Try):
            self._stmts(stmt.body)
            for h in stmt.handlers:
                self._stmts(h.body)
            self._stmts(stmt.orelse)
            self._stmts(stmt.finalbody)
        elif isinstance(stmt, (ast.Return, ast.Expr)):
            self.taint(stmt.value)
        elif isinstance(stmt, ast.Raise):
            self.taint(stmt.exc)
            self.taint(stmt.cause)
        elif isinstance(stmt, ast.Delete):
            pass
        else:
            for child in ast.iter_child_nodes(stmt):
                if isinstance(child, ast.expr):
                    self.taint(child)
                elif isinstance(child, ast.stmt):
                    self._stmt(child)


def check(pf: ParsedFile,
          scope: Dict[str, bool]) -> List[Finding]:
    """Run the taint pass over this file's slice of the package-wide
    traced scope (``qualname -> params_tainted``, computed by
    :meth:`~.callgraph.PackageGraph.traced_scope` — declared roots keep
    their registry mode, nested ``def``s are tainted jit/scan bodies,
    merely-called helpers join untainted)."""
    out: List[Finding] = []
    for q, params_tainted in sorted(scope.items()):
        if q in pf.functions:
            _Taint(pf, pf.functions[q], params_tainted, out).run()
    return out
