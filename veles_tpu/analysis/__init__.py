"""veles_tpu.analysis — trace-discipline and host-concurrency static
analyzer (docs/analysis.md).

Every invariant this codebase lives by — exactly two program kinds per
engine lifetime, flat StepCache counters across rollback/swap/COW,
traced-data-flow-only control decisions, lock-guarded host scheduler
state — was previously enforced only *after the fact* by runtime counter
assertions in tests, which catch a regression only if a test happens to
drive the offending path.  This package enforces them at lint time,
before any test runs, the way the reference project's per-unit
validation hooks checked workflow graphs before a run.

Rule families (full catalogue in docs/analysis.md):

* **trace-safety (VT1xx)** — inside functions reachable from the traced
  program roots (:mod:`veles_tpu.analysis.registry`), flag Python
  ``if``/``while``/``assert`` on tracer-valued expressions, host
  coercions (``float()``/``int()``/``bool()``/``.item()``/
  ``np.asarray()``), host-effect calls (``time.*``/``random.*``/IO),
  and iteration over unordered collections feeding trace order;
* **concurrency discipline (VC2xx)** — fields annotated
  ``# guarded-by: self.<lock>`` must only be touched inside
  ``with self.<lock>:`` in the same method (or a method annotated
  ``# requires-lock: self.<lock>``), ``.acquire()`` without a
  ``try/finally`` release is rejected, and — interprocedurally over
  the module-local call graph — lock-order cycles (VC204) and
  blocking calls under annotated locks (VC205) are deadlock/stall
  findings;
* **config-key drift (VK3xx)** — every ``root.common.*`` key read in
  the package must be declared in ``veles_tpu/config.py`` and appear in
  the docs; declared keys nobody reads are dead;
* **metric-name drift (VM4xx)** — every ``vt_*`` metric registered in
  code (runtime/metrics.py) must appear in docs/observability.md's
  reference table, and every documented name must be registered;
* **sharding/collective discipline (VS5xx)** — collective axis names
  must be declared on the mesh (parallel/mesh.py MeshSpec), raw
  collectives must sit inside a registered ``shard_map`` scope, and
  partition specs may not reference undeclared axes;
* **recompile hazards (VP6xx)** — per-call-varying values must not
  flow into traced-program builder slots, builder bodies must not let
  caller-mapping insertion order become pytree structure, and builders
  reachable from host hot loops must route through StepCache;
* **resource lifecycles (VR7xx)** — declared acquire/release pairs
  (the paged-KV refcounts) must balance on every exit path, spawned
  threads must be daemon or joined somewhere in the package, handles
  must be ``with``/finally-managed, and durable writes must stage
  tmp-fsync-rename.

Every reachability closure above resolves across module boundaries
(:mod:`~.callgraph`): ``from x import y``, module-attribute calls,
and ``self.m()`` through inheritance and subclass overrides, with
per-file summaries cached content-hash-keyed in
``.veles-lint-cache.json`` so the warm gate is sub-second.

Pure ``ast``/``tokenize`` — importing or running this package never
imports jax or any of the modules it analyzes (a lint pass must be
cheap enough to gate every CI run).  CLI::

    python -m veles_tpu.analysis veles_tpu        # or: veles-tpu-lint
    veles-tpu-lint veles_tpu --json
    veles-tpu-lint veles_tpu --write-baseline     # accept current findings

Exit code 0 = no unbaselined findings; 1 = findings; 2 = usage error.
"""

from .baseline import load_baseline, write_baseline
from .engine import analyze_files, iter_python_files, run_analysis
from .findings import Finding

__all__ = ["Finding", "analyze_files", "iter_python_files",
           "load_baseline", "run_analysis", "write_baseline"]
