"""``veles-tpu-lint`` / ``python -m veles_tpu.analysis`` — the CI gate.

Exit code 0 when every finding is suppressed inline or accepted by the
baseline; 1 when new findings exist (print them, fail the build); 2 on
usage errors (argparse).  ``--json`` emits the machine-readable form
the way ``veles-tpu --dump-config`` does for config.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Optional

from .baseline import BASELINE_NAME, write_baseline
from .engine import run_analysis
from .findings import sort_key


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="veles-tpu-lint",
        description="trace-discipline / host-concurrency / config-drift "
                    "/ metric-drift static analyzer for veles_tpu "
                    "(docs/analysis.md)")
    p.add_argument("paths", nargs="*", default=["veles_tpu"],
                   help="files or directories to analyze "
                        "(default: veles_tpu)")
    p.add_argument("--json", action="store_true",
                   help="emit findings as JSON instead of text")
    p.add_argument("--baseline", default="auto", metavar="PATH",
                   help=f"baseline file (default: nearest "
                        f"{BASELINE_NAME} walking up from the first "
                        "path; 'none' disables)")
    p.add_argument("--write-baseline", action="store_true",
                   help="accept every current finding into the baseline "
                        "and exit 0")
    p.add_argument("--docs", default="auto", metavar="DIR",
                   help="docs directory for VK303 (default: nearest "
                        "docs/ dir; 'none' disables the docs check)")
    return p


def main(argv: Optional[list] = None) -> int:
    args = build_parser().parse_args(argv)
    baseline = None if args.baseline == "none" else args.baseline
    docs = None if args.docs == "none" else args.docs
    report = run_analysis(args.paths, baseline_path=baseline,
                          docs_dir=docs)
    if report["files"] == 0:
        # a wrong cwd / typo'd path must not silently DISABLE the gate
        # by "cleanly" analyzing nothing
        print(f"veles-tpu-lint: no Python files under {args.paths!r} "
              "(wrong directory?)", file=sys.stderr)
        return 2

    if args.write_baseline:
        path = report["baseline_path"] or BASELINE_NAME
        n = write_baseline(path, report["all"])
        print(f"baseline: wrote {n} finding(s) to {path}")
        return 0

    new = sorted(report["findings"], key=sort_key)
    if args.json:
        doc = {"findings": [f.to_dict() for f in new],
               "accepted": len(report["accepted"]),
               "files": report["files"],
               "baseline": report["baseline_path"]}
        print(json.dumps(doc, indent=1))
        return 1 if new else 0

    for f in new:
        print(f.format())
    errors = sum(1 for f in new if f.severity == "error")
    warnings = len(new) - errors
    accepted = len(report["accepted"])
    tail = f" ({accepted} accepted by baseline)" if accepted else ""
    if new:
        print(f"\n{errors} error(s), {warnings} warning(s) across "
              f"{report['files']} file(s){tail}")
        return 1
    print(f"clean: 0 findings across {report['files']} file(s){tail}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
