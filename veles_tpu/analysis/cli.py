"""``veles-tpu-lint`` / ``python -m veles_tpu.analysis`` — the CI gate.

Exit code 0 when every finding is suppressed inline or accepted by the
baseline; 1 when new findings exist (print them, fail the build); 2 on
usage errors (argparse).  ``--json`` emits the machine-readable form
(``schema_version`` + per-family counts — the stable contract CI
dashboards chart, asserted by a golden test); ``--changed [BASE]``
lints only the files ``git diff --name-only`` reports, for sub-second
pre-commit runs (.pre-commit-config.yaml ships the hook).
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
from typing import List, Optional

from .baseline import (BASELINE_NAME, load_baseline, prune_missing,
                       write_baseline)
from .engine import run_analysis
from .findings import FAMILIES, family, sort_key

#: bumped whenever a --json key changes meaning or disappears; adding
#: keys is compatible and does not bump it.
SCHEMA_VERSION = 1


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="veles-tpu-lint",
        description="trace-discipline / host-concurrency / config-drift "
                    "/ metric-drift / sharding / recompile-hazard "
                    "static analyzer for veles_tpu (docs/analysis.md)")
    p.add_argument("paths", nargs="*", default=["veles_tpu"],
                   help="files or directories to analyze "
                        "(default: veles_tpu)")
    p.add_argument("--json", action="store_true",
                   help="emit findings as JSON instead of text")
    p.add_argument("--baseline", default="auto", metavar="PATH",
                   help=f"baseline file (default: nearest "
                        f"{BASELINE_NAME} walking up from the first "
                        "path; 'none' disables)")
    p.add_argument("--write-baseline", action="store_true",
                   help="accept every current finding into the baseline "
                        "(pruning entries whose file no longer exists) "
                        "and exit 0")
    p.add_argument("--docs", default="auto", metavar="DIR",
                   help="docs directory for VK303 (default: nearest "
                        "docs/ dir; 'none' disables the docs check)")
    p.add_argument("--changed", nargs="?", const="HEAD", default=None,
                   metavar="BASE",
                   help="lint only the .py files `git diff --name-only "
                        "BASE` reports (default BASE: HEAD — the "
                        "working tree's changes), restricted to the "
                        "positional path scope (default veles_tpu) "
                        "when it exists — so the hook and the CI gate "
                        "agree on what is clean; zero changed files "
                        "is a clean exit, not a usage error.  The "
                        "unchanged files still feed the cross-module "
                        "closure through cached summaries")
    p.add_argument("--no-cache", action="store_true",
                   help="ignore and do not write the summary cache "
                        "(.veles-lint-cache.json — content-hash keyed, "
                        "safe to delete any time)")
    p.add_argument("--local", action="store_true",
                   help="restrict every closure to module-local reach "
                        "(the pre-cross-module analyzer) — for "
                        "bisecting whether a finding needs the "
                        "package-wide graph")
    return p


def _changed_paths(base: str, anchors: List[str]) -> Optional[List[str]]:
    """Changed ``.py`` files from git (tracked diffs + untracked), as
    absolute paths; None when git is unavailable / not a repository."""
    cwd = None
    for a in anchors:
        a = os.path.abspath(a)
        cwd = a if os.path.isdir(a) else os.path.dirname(a)
        if os.path.isdir(cwd):
            break
    try:
        top = subprocess.run(
            ["git", "rev-parse", "--show-toplevel"], cwd=cwd,
            capture_output=True, text=True, timeout=30)
        if top.returncode != 0:
            return None
        root = top.stdout.strip()
        diff = subprocess.run(
            ["git", "diff", "--name-only", "--diff-filter=d", base],
            cwd=root, capture_output=True, text=True, timeout=30)
        if diff.returncode != 0:
            return None
        extra = subprocess.run(
            ["git", "ls-files", "--others", "--exclude-standard"],
            cwd=root, capture_output=True, text=True, timeout=30)
        names = diff.stdout.splitlines() + (
            extra.stdout.splitlines() if extra.returncode == 0 else [])
    except (OSError, subprocess.SubprocessError):
        return None
    out = []
    for name in names:
        if not name.endswith(".py"):
            continue
        full = os.path.join(root, name)
        if os.path.isfile(full) and full not in out:
            out.append(full)
    return sorted(out)


def _empty_json_doc() -> dict:
    return {"schema_version": SCHEMA_VERSION, "findings": [],
            "by_family": {fam: 0 for fam in FAMILIES},
            "accepted": 0, "files": 0, "baseline": None}


def main(argv: Optional[list] = None) -> int:
    args = build_parser().parse_args(argv)
    baseline = None if args.baseline == "none" else args.baseline
    docs = None if args.docs == "none" else args.docs
    cache = None if args.no_cache else "auto"
    cross = not args.local
    paths = args.paths
    scope_paths = None
    if args.changed is not None:
        changed = _changed_paths(args.changed, paths)
        if changed is None:
            print("veles-tpu-lint: --changed needs a git repository",
                  file=sys.stderr)
            return 2
        # restrict to the positional scope (default veles_tpu) so the
        # pre-commit hook and the CI gate agree on what is clean —
        # but only where those anchors exist (a bare repo without the
        # default package keeps the unrestricted behavior)
        anchors = [os.path.abspath(p) for p in paths
                   if os.path.exists(p)]
        if anchors:
            changed = [f for f in changed
                       if any(f == a or f.startswith(a + os.sep)
                              for a in anchors)]
        if not changed:
            if args.json:
                print(json.dumps(_empty_json_doc(), indent=1))
            else:
                print("clean: no changed Python files")
            return 0
        # the unchanged rest of the scope still feeds the cross-module
        # closure (cached summaries; parsed once on a cold cache)
        scope_paths = anchors or None
        paths = changed
    report = run_analysis(paths, baseline_path=baseline, docs_dir=docs,
                          cache_path=cache, scope_paths=scope_paths,
                          cross_module=cross)
    if report["files"] == 0:
        # a wrong cwd / typo'd path must not silently DISABLE the gate
        # by "cleanly" analyzing nothing
        print(f"veles-tpu-lint: no Python files under {paths!r} "
              "(wrong directory?)", file=sys.stderr)
        return 2

    if args.write_baseline:
        path = report["baseline_path"] or BASELINE_NAME
        keep = []
        prior = load_baseline(report["baseline_path"])
        if prior:
            base_dir = os.path.dirname(os.path.abspath(path))
            before = len(prior)
            kept_entries = prune_missing(prior.values(), base_dir)
            pruned = before - len(kept_entries)
            # keep prior debt for files outside this scan; scanned
            # files are fully re-derived from the current findings
            analyzed = {rel.replace(os.sep, "/") for rel in
                        (r for _p, r in _scan_rels(paths))}
            keep = [e for e in kept_entries
                    if e.get("path") not in analyzed]
            if pruned:
                print(f"baseline: pruned {pruned} entr"
                      f"{'y' if pruned == 1 else 'ies'} whose file no "
                      "longer exists")
        n = write_baseline(path, report["all"], keep=keep)
        print(f"baseline: wrote {n} finding(s) to {path}")
        return 0

    new = sorted(report["findings"], key=sort_key)
    if args.json:
        counts = {fam: 0 for fam in FAMILIES}
        for f in new:
            counts[family(f.rule)] = counts.get(family(f.rule), 0) + 1
        doc = {"schema_version": SCHEMA_VERSION,
               "findings": [f.to_dict() for f in new],
               "by_family": counts,
               "accepted": len(report["accepted"]),
               "files": report["files"],
               "baseline": report["baseline_path"]}
        print(json.dumps(doc, indent=1))
        return 1 if new else 0

    for f in new:
        print(f.format())
    errors = sum(1 for f in new if f.severity == "error")
    warnings = len(new) - errors
    accepted = len(report["accepted"])
    tail = f" ({accepted} accepted by baseline)" if accepted else ""
    if new:
        print(f"\n{errors} error(s), {warnings} warning(s) across "
              f"{report['files']} file(s){tail}")
        return 1
    print(f"clean: 0 findings across {report['files']} file(s){tail}")
    return 0


def _scan_rels(paths):
    from .engine import iter_python_files
    return iter_python_files(paths)


if __name__ == "__main__":
    sys.exit(main())
