"""VP6xx — recompile-hazard analysis at builder call sites.

The two-program-kind compile discipline (docs/serving.md; StepCache's
flat counters) holds only while every *builder* — a registry
``BUILDER`` root whose arguments are static Python baked into the
traced program — is fed genuinely static values and invoked through a
compile cache.  The def-site convention (static knobs are
keyword-only) is enforced by the VT1xx taint pass; this family
enforces the same contract at the CALL site:

VP601  a per-call-varying Python value — a loop variable, the
       ``len()`` of a runtime collection, a ``time``/``uuid``/
       ``random``-derived value, or anything assigned from one —
       flowing into a builder argument slot.  Every distinct value is
       a distinct traced program: a cache key at best, an unbounded
       recompile stream at worst — error.  Bounded static inventories
       (the prefill bucket table) are the legitimate exception and
       carry an inline ``# lint: disable=VP601 <why static>``.
VP602  dict/set iteration constructing pytree structure inside a
       builder body: the caller's mapping insertion order becomes the
       pytree (and therefore cache-key) order — an invisible cache key
       that differs between processes doing the same work in a
       different order.  ``sorted(...)`` fixes it — warning.
       (Unordered-*set* iteration inside traced scope is VT104's; this
       rule covers the caller-supplied-mapping case VT104 cannot see.)
VP603  a builder reachable from a host hot loop (the engine scheduler
       tick, a REST request handler — ``HOST_LOOP_ROOTS``, closed
       package-wide) that is not routed through ``StepCache
       .get_step`` or a registry-declared self-caching builder
       (``SELF_CACHING_BUILDERS``): a lazy recompile smuggled past
       the counters every test asserts flat — error.

Builder names come from the registry (``TRACE_ROOTS`` entries in
``BUILDER`` mode) plus per-file ``# trace-root: builder`` markers;
call sites match on the final name (``self.plan.init_caches`` matches
the ``DecodePlan.init_caches`` root), while the host-loop reach and
the program-composition exemption both close over the package call
graph (analysis/callgraph.py) — a builder invoked from a REST handler
through a helper module, or from an ``ArtifactRunner`` override of an
engine hook, is still caught.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from .findings import Finding
from .pysrc import FnInfo, ParsedFile, dotted_name
from .registry import (BUILDER, SELF_CACHING_BUILDERS, TRACE_ROOTS)

#: modules whose call results vary per call (VP601 taint sources).
_VARYING_MODULES = ("time", "uuid", "random", "secrets", "datetime")


def builder_names(graph) -> Set[str]:
    """Final names of every registered BUILDER root (global registry +
    inline ``# trace-root: builder`` markers anywhere in the package —
    summaries included, so a cached unparsed module's builders still
    bind call sites in the files under analysis)."""
    names: Set[str] = set()
    for entry in TRACE_ROOTS.values():
        for q, mode in entry.items():
            if mode == BUILDER:
                names.add(q.split(".")[-1])
    for s in graph.summaries.values():
        for q, mode in s["markers"]["trace"].items():
            if mode == BUILDER:
                names.add(q.split(".")[-1])
    return names


def _call_final_name(node: ast.Call) -> Optional[str]:
    if isinstance(node.func, ast.Name):
        return node.func.id
    if isinstance(node.func, ast.Attribute):
        return node.func.attr
    return None


def _is_test_file(pf: ParsedFile) -> bool:
    """The compile discipline binds the PRODUCT: tests loop builders
    over geometries on purpose (parameterized compile coverage), so
    the VP6xx family skips them — the same reasoning as VM402's
    package-scan gate."""
    parts = pf.relpath.split("/")
    return "tests" in parts[:-1] or parts[-1].startswith("test_") \
        or parts[-1] == "conftest.py"


def check(files: List[ParsedFile], graph) -> List[Finding]:
    """``graph`` is the :class:`~.callgraph.PackageGraph`: the
    program-composition exemption (builder calls inside one program
    build) and the VP603 host-loop reach both close over it, so a
    builder invoked from a REST handler through a helper module is
    still caught, and a builder composed by another module's builder is
    still exempt."""
    files = [pf for pf in files if not _is_test_file(pf)]
    builders = builder_names(graph)
    program = graph.program_scope()
    host = graph.host_scope() - program
    out: List[Finding] = []
    for pf in files:
        pscope = {q for (rel, q) in program if rel == pf.relpath}
        hscope = {q for (rel, q) in host if rel == pf.relpath}
        _vp601_file(pf, builders, out, pscope)
        _vp602_file(pf, out)
        _vp603_file(pf, builders, out, hscope)
    return out


# -- VP601: varying values into builder slots --------------------------------

class _VaryTaint:
    """Statement-order varying-value taint over one function body:
    sources are loop targets, ``len()`` results and ``time``/``uuid``/
    ``random`` calls; propagation follows assignments and arithmetic.
    Deliberately join-free, like the VT1xx pass."""

    def __init__(self, pf: ParsedFile, info: FnInfo,
                 builders: Set[str], out: List[Finding]):
        self.pf = pf
        self.info = info
        self.builders = builders
        self.out = out
        self.env: Set[str] = set()
        #: name -> why it varies (for the message)
        self.why: Dict[str, str] = {}

    def _emit(self, node: ast.AST, what: str):
        self.out.append(Finding(
            rule="VP601", path=self.pf.relpath, line=node.lineno,
            col=node.col_offset,
            message=f"per-call-varying value ({what}) flows into a "
                    "static argument slot of a traced-program builder "
                    "— every distinct value traces and compiles a new "
                    "program",
            hint="hoist the varying value out (pass it as traced data),"
                 " or justify a bounded inventory inline "
                 "(`# lint: disable=VP601 <why the set is static>`)",
            symbol=self.info.qualname,
            snippet=self.pf.line_text(node.lineno)))

    # returns a description of why the expression varies, or None
    def varies(self, node: Optional[ast.AST]) -> Optional[str]:
        if node is None or isinstance(node, ast.Constant):
            return None
        if isinstance(node, ast.Name):
            return self.why.get(node.id) if node.id in self.env else None
        if isinstance(node, ast.Call):
            name = _call_final_name(node)
            if isinstance(node.func, ast.Name) and node.func.id == "len":
                return "len() of a runtime collection"
            chain = dotted_name(node.func)
            if chain is not None:
                head = self.pf.resolve_chain(chain).split(".")[0]
                if head in _VARYING_MODULES:
                    return f"`{chain}(...)` result"
            for a in list(node.args) + [k.value for k in node.keywords]:
                w = self.varies(a)
                if w:
                    return w
            return None
        if isinstance(node, ast.BinOp):
            return self.varies(node.left) or self.varies(node.right)
        if isinstance(node, ast.UnaryOp):
            return self.varies(node.operand)
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            for e in node.elts:
                w = self.varies(e)
                if w:
                    return w
            return None
        if isinstance(node, ast.IfExp):
            return self.varies(node.body) or self.varies(node.orelse)
        if isinstance(node, ast.Subscript):
            return self.varies(node.value) or self.varies(node.slice)
        return None

    def _assign(self, target: ast.AST, why: Optional[str]):
        if isinstance(target, ast.Name):
            if why:
                self.env.add(target.id)
                self.why[target.id] = why
            else:
                self.env.discard(target.id)
                self.why.pop(target.id, None)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for e in target.elts:
                self._assign(e, why)
        elif isinstance(target, ast.Starred):
            self._assign(target.value, why)

    def _check_call(self, node: ast.Call):
        name = _call_final_name(node)
        if name not in self.builders:
            return
        for a in list(node.args) + [k.value for k in node.keywords]:
            w = self.varies(a)
            if w:
                self._emit(node, w)
                return      # one finding per call site

    def run(self):
        self._stmts(self.info.node.body)

    def _stmts(self, body):
        for stmt in body:
            self._stmt(stmt)

    def _stmt(self, stmt: ast.stmt):
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return                  # nested defs get their own walk
        if isinstance(stmt, ast.For):
            self._scan_calls(stmt.iter)
            self._assign(stmt.target, "loop variable")
            self._stmts(stmt.body)
            self._stmts(stmt.orelse)
            return
        if isinstance(stmt, ast.Assign):
            self._scan_calls(stmt.value)
            why = self.varies(stmt.value)
            for t in stmt.targets:
                self._assign(t, why)
            return
        if isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            self._scan_calls(stmt.value)
            self._assign(stmt.target, self.varies(stmt.value))
            return
        if isinstance(stmt, ast.AugAssign):
            self._scan_calls(stmt.value)
            why = self.varies(stmt.value)
            if why and isinstance(stmt.target, ast.Name):
                self.env.add(stmt.target.id)
                self.why[stmt.target.id] = why
            return
        # other statements: scan expressions for builder calls, recurse
        for child in ast.iter_child_nodes(stmt):
            if isinstance(child, ast.expr):
                self._scan_calls(child)
            elif isinstance(child, ast.stmt):
                self._stmt(child)

    def _scan_calls(self, node: ast.AST):
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call):
                self._check_call(sub)
            elif isinstance(sub, (ast.ListComp, ast.SetComp,
                                  ast.GeneratorExp, ast.DictComp)):
                # comprehension targets vary per element; builder calls
                # inside the element expression see that
                for gen in sub.generators:
                    self._assign(gen.target, "comprehension variable")


def _vp601_file(pf: ParsedFile, builders: Set[str],
                out: List[Finding], program_scope: Set[str]):
    if not builders or not any(b in pf.source for b in builders):
        return
    for q, info in pf.functions.items():
        if q in program_scope:
            continue    # build-time composition inside one program
        _VaryTaint(pf, info, builders, out).run()


# -- VP602: mapping-order pytree structure inside builders -------------------

def _builder_scope(pf: ParsedFile) -> Set[str]:
    """BUILDER-mode roots of this file (registry longest-suffix entry +
    inline markers).  The roots THEMSELVES, not their nested defs —
    nested defs are the traced programs, where VT104 owns iteration
    order."""
    table = {}
    best = ""
    for key, entry in TRACE_ROOTS.items():
        if (pf.relpath == key or pf.relpath.endswith("/" + key)) \
                and len(key) > len(best):
            best, table = key, dict(entry)
    roots = {q for q, mode in table.items()
             if mode == BUILDER and q in pf.functions}
    for q, info in pf.functions.items():
        if pf.comments.trace_root.get(info.node.lineno) == "builder":
            roots.add(q)
    return roots


def _vp602_file(pf: ParsedFile, out: List[Finding]):
    for q in sorted(_builder_scope(pf)):
        info = pf.functions[q]
        params = {a.arg for a in (
            list(info.node.args.posonlyargs) + list(info.node.args.args)
            + list(info.node.args.kwonlyargs))} - {"self", "cls"}

        def param_mapping_iter(it: ast.AST) -> Optional[str]:
            """The parameter name when ``it`` iterates a caller-supplied
            mapping (``p`` / ``p.items()`` / ``p.keys()`` /
            ``p.values()``), else None."""
            if isinstance(it, ast.Name) and it.id in params:
                return it.id
            if isinstance(it, ast.Call) \
                    and isinstance(it.func, ast.Attribute) \
                    and it.func.attr in ("items", "keys", "values") \
                    and isinstance(it.func.value, ast.Name) \
                    and it.func.value.id in params:
                return it.func.value.id
            return None

        for node in ast.walk(info.node):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node is not info.node:
                continue        # children walked via ast.walk anyway —
            iters = []          # nested defs excluded below by line
            if isinstance(node, ast.For):
                iters = [(node.iter, node)]
            elif isinstance(node, (ast.ListComp, ast.SetComp,
                                   ast.GeneratorExp, ast.DictComp)):
                iters = [(g.iter, node) for g in node.generators]
            for it, at in iters:
                if not _line_in_own_body(pf, info, at.lineno):
                    continue
                name = param_mapping_iter(it)
                if name is None:
                    continue
                out.append(Finding(
                    rule="VP602", path=pf.relpath, line=at.lineno,
                    col=at.col_offset,
                    message=f"builder iterates caller-supplied mapping "
                            f"`{name}` — its insertion order becomes "
                            "pytree structure order, an invisible "
                            "compile-cache key",
                    hint=f"iterate `sorted({name}.items())` (or take a "
                         "static sequence) so two processes building "
                         "the same program emit the same structure",
                    symbol=q, snippet=pf.line_text(at.lineno)))


def _line_in_own_body(pf: ParsedFile, info: FnInfo, line: int) -> bool:
    """True when ``line`` is in the function's own body, not inside one
    of its nested ``def``s (those are traced programs, not build
    code)."""
    for q2, i2 in pf.functions.items():
        if i2.node is info.node:
            continue
        if not q2.startswith(info.qualname + "."):
            continue
        end = getattr(i2.node, "end_lineno", i2.node.lineno)
        if i2.node.lineno <= line <= end:
            return False
    return True


# -- VP603: builders reachable from host loops, outside StepCache ------------

def _vp603_file(pf: ParsedFile, builders: Set[str],
                out: List[Finding], scope: Set[str]):
    if not scope or not builders:
        return
    # parent chain for the routed-through-StepCache check
    parents: Dict[int, ast.AST] = {}
    for parent in ast.walk(pf.tree):
        for child in ast.iter_child_nodes(parent):
            parents[id(child)] = parent

    def routed_through_cache(node: ast.AST) -> bool:
        cur = node
        while cur is not None:
            cur = parents.get(id(cur))
            if isinstance(cur, ast.Call):
                chain = dotted_name(cur.func)
                if chain and chain.split(".")[-1] == "get_step":
                    return True
        return False

    for q in sorted(scope):
        if q not in pf.functions:
            continue
        info = pf.functions[q]
        for node in ast.walk(info.node):
            if not isinstance(node, ast.Call):
                continue
            name = _call_final_name(node)
            if name not in builders or name in SELF_CACHING_BUILDERS:
                continue
            if routed_through_cache(node):
                continue
            out.append(Finding(
                rule="VP603", path=pf.relpath, line=node.lineno,
                col=node.col_offset,
                message=f"builder `{name}` is reachable from a host "
                        "hot loop (scheduler/REST) without routing "
                        "through StepCache — a lazy recompile the flat "
                        "compile counters never see",
                hint="fetch the program via step_cache.get_step(...) "
                     "(or register the builder's own memo in "
                     "registry.SELF_CACHING_BUILDERS with a docstring "
                     "naming its cache)",
                symbol=q, snippet=pf.line_text(node.lineno)))
