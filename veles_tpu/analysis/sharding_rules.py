"""VS5xx — sharding / collective discipline.

Every mesh-axis name in this codebase is a string handed to a
collective (``jax.lax.psum(x, "data")``), a partition spec
(``P(None, "seq")``) or a ``shard_map`` — and none of them fail at
parse time when they drift from the axes the mesh actually declares:
an undeclared axis is a runtime ``NameError`` deep inside a trace (at
best) or a silently-replicated tensor (at worst, with
``check_vma=False``).  GSPMD's lesson (PAPERS.md: arXiv 2105.04663)
is that sharding bugs are *propagation* bugs — exactly what a static
pass over the annotations catches before any device is touched.

Three sources of truth are cross-referenced:

* **declared axes** — collected statically from ``parallel/mesh.py``
  (the ``MeshSpec`` dataclass fields and tuple-of-string axis-name
  arguments to ``Mesh(...)`` constructors) and from ``config.py``
  (keys of the ``root.common.mesh`` default dict);
* **shard-map scope** — the registry's ``SHARD_MAP_ROOTS`` (plus
  inline ``# shard-map-root: axis[,axis]`` markers), closed over the
  package call graph exactly like the trace roots: nested ``def``s and
  called helpers — in any module — join the scope, each with the axis
  environment of the roots that actually reach it;
* **use sites** — ``jax.lax`` collective calls (``COLLECTIVE_OPS``)
  and ``PartitionSpec``/``P`` constructions.

VS501  a collective whose literal axis name is declared on no mesh —
       or, inside a shard-map scope with a declared axis environment,
       names an axis that scope does not bind — error.
VS502  a collective call outside any shard-map scope: raw named-axis
       collectives need the manual axis binding ``shard_map`` (or a
       schedule's ``Context.manual_axes``) provides; under plain jit
       they fail at trace time on the deployment that first reaches
       them — error.
VS503  a ``PartitionSpec`` (``P(...)``, ``with_sharding_constraint``
       / ``NamedSharding`` included transitively — the spec is where
       the literal lives) naming an undeclared axis — error.

VS501/VS503 only fire when the scan actually found axis declarations
(a subset scan without mesh.py cannot prove "undeclared", the VK302
bail-out pattern); VS502 needs no declarations — scope is the check.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Optional, Set, Tuple

from .findings import Finding
from .pysrc import ParsedFile, dotted_name
from .registry import COLLECTIVE_OPS

#: cheap textual pre-filter: a file mentioning none of these cannot
#: produce a VS5xx finding, so the AST passes skip it entirely.
_MAYBE_RE = re.compile(
    r"\b(" + "|".join(sorted(COLLECTIVE_OPS)) + r"|PartitionSpec)\b")


def collect_declared_axes(files: List[ParsedFile]) -> Set[str]:
    """Mesh axis names declared anywhere in the scanned set: MeshSpec
    dataclass fields (mesh.py), tuple-of-strings axis arguments to
    ``Mesh(...)``, and keys of ``root.common.mesh`` config defaults."""
    axes: Set[str] = set()
    for pf in files:
        base = os.path.basename(pf.relpath)
        if base == "mesh.py":
            for node in ast.walk(pf.tree):
                if isinstance(node, ast.ClassDef) \
                        and node.name == "MeshSpec":
                    for stmt in node.body:
                        if isinstance(stmt, ast.AnnAssign) \
                                and isinstance(stmt.target, ast.Name):
                            axes.add(stmt.target.id)
                        elif isinstance(stmt, ast.Assign):
                            for t in stmt.targets:
                                if isinstance(t, ast.Name):
                                    axes.add(t.id)
        # Mesh(devices, ("data", ...)) call sites — any file
        if "Mesh(" in pf.source:
            for node in ast.walk(pf.tree):
                if isinstance(node, ast.Call):
                    chain = dotted_name(node.func)
                    if chain and chain.split(".")[-1] == "Mesh" \
                            and len(node.args) >= 2:
                        axes |= _literal_strs(node.args[1])
        if base == "config.py":
            # root.common.mesh = dict(data=-1) / {"data": -1}
            for node in ast.walk(pf.tree):
                if not isinstance(node, ast.Assign):
                    continue
                for t in node.targets:
                    dotted = dotted_name(t)
                    if dotted and dotted.endswith(".mesh"):
                        axes |= _dict_keys(node.value)
    return axes


def _literal_strs(node: ast.AST) -> Set[str]:
    out: Set[str] = set()
    if isinstance(node, (ast.Tuple, ast.List)):
        for e in node.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, str):
                out.add(e.value)
    elif isinstance(node, ast.Constant) and isinstance(node.value, str):
        out.add(node.value)
    return out


def _dict_keys(node: ast.AST) -> Set[str]:
    out: Set[str] = set()
    if isinstance(node, ast.Dict):
        for k in node.keys:
            if isinstance(k, ast.Constant) and isinstance(k.value, str):
                out.add(k.value)
    elif isinstance(node, ast.Call) and isinstance(node.func, ast.Name) \
            and node.func.id == "dict":
        for kw in node.keywords:
            if kw.arg:
                out.add(kw.arg)
    return out


def _collective_axis_literals(pf: ParsedFile,
                              node: ast.Call) -> Tuple[str, Set[str]]:
    """(op name, literal axis strings) for a jax.lax collective call;
    op is "" when the call is not a collective."""
    chain = dotted_name(node.func)
    if chain is None:
        return "", set()
    resolved = pf.resolve_chain(chain)
    leaf = resolved.split(".")[-1]
    if leaf not in COLLECTIVE_OPS:
        return "", set()
    # only count the op when it comes from jax.lax (or is imported from
    # it): a method named .psum on some object is not a collective
    head = resolved.split(".")[0]
    if head not in ("jax", "lax") and "lax" not in resolved.split("."):
        return "", set()
    idx = COLLECTIVE_OPS[leaf]
    axes: Set[str] = set()
    if len(node.args) > idx:
        axes |= _literal_strs(node.args[idx])
    for kw in node.keywords:
        if kw.arg in ("axis_name", "axis_names"):
            axes |= _literal_strs(kw.value)
    return leaf, axes


def check(files: List[ParsedFile], graph) -> List[Finding]:
    """``graph`` is the :class:`~.callgraph.PackageGraph`; shard-map
    scope closes over it, so a collective in a helper module called
    from a registered schedule body is in scope (and checked against
    that root's axis environment) without its own registry entry."""
    declared = collect_declared_axes(files)
    shard_env = graph.shard_scope()
    out: List[Finding] = []
    for pf in files:
        if _MAYBE_RE.search(pf.source):
            scope_env = {q: axes for (rel, q), axes in shard_env.items()
                         if rel == pf.relpath}
            out.extend(_check_file(pf, declared, scope_env))
    return out


def _check_file(pf: ParsedFile, declared: Set[str],
                scope_env: Dict[str, Tuple[str, ...]]) -> List[Finding]:
    out: List[Finding] = []
    scope = set(scope_env)

    # function spans for symbol attribution
    def symbol_at(line: int) -> str:
        best, best_span = "", None
        for q, info in pf.functions.items():
            node = info.node
            end = getattr(node, "end_lineno", node.lineno)
            if node.lineno <= line <= end:
                span = end - node.lineno
                if best_span is None or span < best_span:
                    best, best_span = q, span
        return best

    in_scope_lines: List[Tuple[int, int, str]] = []
    for q in scope:
        if q not in pf.functions:
            continue
        node = pf.functions[q].node
        in_scope_lines.append(
            (node.lineno, getattr(node, "end_lineno", node.lineno), q))

    def in_scope(line: int) -> bool:
        return any(lo <= line <= hi for lo, hi, _q in in_scope_lines)

    def env_at(line: int) -> Tuple[str, ...]:
        """Axis environment of the innermost enclosing in-scope
        function (per-root envs, not a file-wide union)."""
        best, span = (), None
        for lo, hi, q in in_scope_lines:
            if lo <= line <= hi and (span is None or hi - lo < span):
                best, span = scope_env.get(q, ()), hi - lo
        return best

    for node in ast.walk(pf.tree):
        if not isinstance(node, ast.Call):
            continue
        op, axes = _collective_axis_literals(pf, node)
        if op:
            if not in_scope(node.lineno):
                out.append(Finding(
                    rule="VS502", path=pf.relpath, line=node.lineno,
                    col=node.col_offset,
                    message=f"collective `{op}` outside any shard_map/"
                            "schedule traced scope — raw named-axis "
                            "collectives need the manual axis binding "
                            "a shard_map body provides",
                    hint="move it into a shard_map-wrapped body and "
                         "declare the root in analysis/registry.py "
                         "SHARD_MAP_ROOTS (or `# shard-map-root: "
                         "axis` on the def line)",
                    symbol=symbol_at(node.lineno),
                    snippet=pf.line_text(node.lineno)))
            for axis in sorted(axes):
                if declared and axis not in declared:
                    out.append(Finding(
                        rule="VS501", path=pf.relpath, line=node.lineno,
                        col=node.col_offset,
                        message=f"collective `{op}` names axis "
                                f"`{axis}`, which no mesh declares "
                                f"(declared: {sorted(declared)})",
                        hint="fix the axis name, or declare it on the "
                             "MeshSpec in parallel/mesh.py",
                        symbol=symbol_at(node.lineno),
                        snippet=pf.line_text(node.lineno)))
                elif (env := env_at(node.lineno)) and axis in declared \
                        and axis not in env:
                    out.append(Finding(
                        rule="VS501", path=pf.relpath, line=node.lineno,
                        col=node.col_offset,
                        message=f"collective `{op}` names axis "
                                f"`{axis}`, which this shard_map scope "
                                f"does not bind (environment: "
                                f"{sorted(env)})",
                        hint="bind the axis in the shard_map (and its "
                             "registry entry) or fix the name",
                        symbol=symbol_at(node.lineno),
                        snippet=pf.line_text(node.lineno)))
            continue
        # VS503: PartitionSpec literals (P("data", None), NamedSharding
        # and with_sharding_constraint reach here through the P inside)
        if not declared:
            continue
        chain = dotted_name(node.func)
        if chain is None:
            continue
        resolved = pf.resolve_chain(chain)
        if resolved.split(".")[-1] not in ("PartitionSpec",):
            continue
        spec_axes: Set[str] = set()
        for a in node.args:
            spec_axes |= _literal_strs(a)
        for axis in sorted(spec_axes):
            if axis not in declared:
                out.append(Finding(
                    rule="VS503", path=pf.relpath, line=node.lineno,
                    col=node.col_offset,
                    message=f"partition spec names axis `{axis}`, "
                            f"which no mesh declares (declared: "
                            f"{sorted(declared)})",
                    hint="fix the axis name, or declare it on the "
                         "MeshSpec in parallel/mesh.py",
                    symbol=symbol_at(node.lineno),
                    snippet=pf.line_text(node.lineno)))
    return out
