"""``python -m veles_tpu.analysis`` — same contract as the
``veles-tpu-lint`` console script (analysis/cli.py)."""

import sys

from .cli import main

sys.exit(main())
