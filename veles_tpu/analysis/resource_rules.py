"""VR7xx — resource-lifecycle rules over the package call graph.

The runtime's correctness depends on resources whose acquire and
release live in *different* functions — often different modules: KV
pages refcounted by the engine scheduler and released on four distinct
exit paths, threads spawned in six modules that must not outlive
shutdown, file/socket handles, and durability-critical writes that must
stage through tmp-fsync-rename.  The whole-package resolution layer
(:mod:`~.callgraph`) makes those lifecycles checkable:

VR701  **acquire/release pairing** for declared resources (the
       registry's ``RESOURCE_PAIRS``; fixtures mark functions with
       ``# resource-acquire: NAME`` / ``# resource-release: NAME``):

       * every declared *exit root* (retire, mid-flight deadline,
         fail-all/crash) must transitively reach a release of the
         resource — a refactor that stops ``_fail_all`` from dropping
         page refs fires here, at the exit root's ``def`` line;
       * after a call to an acquire function, a ``raise`` before the
         acquired state is released or transferred (stored into an
         attribute/subscript, or the function returns it) leaks the
         resource on that error path — unless the raise is covered by
         a ``try`` whose handler/finally reaches a release (directly
         or through the call graph).  Error — a leaked page is pool
         capacity gone until restart.

VR702  **thread lifecycle**: every ``threading.Thread(...)`` started in
       the package must be ``daemon=True`` (or ``.daemon = True``
       before start) or provably ``.join()``-ed somewhere in the
       package (shutdown path).  A non-daemon, never-joined thread
       blocks interpreter exit forever.  Needs whole-package proof, so
       subset scans (``--changed``) skip it, like VM402.

VR703  **unclosed handles**: an ``open()``/``socket.socket()`` result
       neither managed by ``with`` nor closed in a ``try/finally``
       (a bare trailing ``.close()`` leaks on any exception between),
       nor transferred out (returned / stored on an object).  Warning.

VR704  **non-atomic durable writes**: in the declared export/snapshot
       modules (``DURABLE_WRITE_MODULES``; fixture marker
       ``# durable-write:`` on a def line), a file write must follow
       the established tmp-fsync-rename idiom — stage to a tmp name
       and/or ``os.replace``/``os.rename`` into place.  A plain
       ``open(path, "w")`` can leave a half-written artifact that a
       reader trusts.  Error.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .findings import Finding
from .pysrc import ParsedFile, dotted_name
from .registry import DURABLE_WRITE_MODULES, RESOURCE_PAIRS

#: handle constructors VR703 tracks (resolved through import aliases).
_HANDLE_CALLS = {
    "open", "io.open", "gzip.open", "tokenize.open", "socket.socket",
    "socket.create_connection",
}



def _is_test_file(pf: ParsedFile) -> bool:
    parts = pf.relpath.split("/")
    return "tests" in parts[:-1] or parts[-1].startswith("test_") \
        or parts[-1] == "conftest.py"


def _final_name(node: ast.Call) -> Optional[str]:
    if isinstance(node.func, ast.Name):
        return node.func.id
    if isinstance(node.func, ast.Attribute):
        return node.func.attr
    return None


def check(files: List[ParsedFile], graph, *,
          package_scan: Optional[bool] = None) -> List[Finding]:
    files = [pf for pf in files if not _is_test_file(pf)]
    out: List[Finding] = []
    _vr701(files, graph, out)
    if package_scan is not False:
        _vr702(files, graph, out)
    for pf in files:
        _vr703_file(pf, out)
        _vr704_file(pf, out)
    return out


# -- VR701: declared resource acquire/release pairing ------------------------

def _resource_sets(graph):
    """Per resource: acquire / release / exit-root (rel, qual) sets,
    from the registry plus the fixture comment markers."""
    res: Dict[str, Dict[str, Set[Tuple[str, str]]]] = {}

    def bucket(name):
        return res.setdefault(name, {"acquire": set(), "release": set(),
                                     "exit_roots": set()})

    for name, decl in RESOURCE_PAIRS.items():
        b = bucket(name)
        for kind in ("acquire", "release", "exit_roots"):
            for mod, quals in decl.get(kind, {}).items():
                for rel, s in graph.summaries.items():
                    if rel == mod or rel.endswith("/" + mod):
                        for q in quals:
                            if q in s["defs"]:
                                b[kind].add((rel, q))
    for rel, s in graph.summaries.items():
        for q, name in s["markers"]["acquire"].items():
            bucket(name)["acquire"].add((rel, q))
        for q, name in s["markers"]["release"].items():
            bucket(name)["release"].add((rel, q))
    return res


def _release_reaching(graph, releases: Set[Tuple[str, str]]
                      ) -> Set[Tuple[str, str]]:
    """Functions that (transitively) call a release function —
    computed by one reverse fixpoint over resolved references.  A call
    whose receiver is not statically resolvable (``pool.free(h)``)
    seeds by its final name, the VP603 matching convention."""
    rel_names = {q.split(".")[-1] for _rel, q in releases}
    callers: Dict[Tuple[str, str], Set[Tuple[str, str]]] = {}
    seeds: Set[Tuple[str, str]] = set(releases)
    for rel, s in graph.summaries.items():
        for q in s["defs"]:
            cls = s["cls_of"].get(q) or None
            for raw, _line in s["refs"].get(q, ()):
                for tgt in graph.resolve(rel, cls, raw):
                    callers.setdefault(tgt, set()).add((rel, q))
            if rel_names.intersection(s.get("fincalls", {}).get(q, ())):
                seeds.add((rel, q))
            # a nested def executes inside its parent: the parent
            # reaches whatever the child reaches
            if "." in q:
                parent = q.rsplit(".", 1)[0]
                if parent in s["defs"]:
                    callers.setdefault((rel, q), set()).add((rel, parent))
    reach = set(seeds)
    work = list(seeds)
    while work:
        tgt = work.pop()
        for caller in callers.get(tgt, ()):
            if caller not in reach:
                reach.add(caller)
                work.append(caller)
    return reach


def _vr701(files: List[ParsedFile], graph, out: List[Finding]):
    resources = _resource_sets(graph)
    if not resources:
        return
    parsed = {pf.relpath: pf for pf in files}
    for name, sets in sorted(resources.items()):
        if not sets["acquire"] or not sets["release"]:
            continue
        reaching = _release_reaching(graph, sets["release"])
        # (1) every declared exit root must reach a release
        for rel, q in sorted(sets["exit_roots"]):
            pf = parsed.get(rel)
            if pf is None or q not in pf.functions:
                continue
            if (rel, q) not in reaching:
                line = pf.functions[q].node.lineno
                out.append(Finding(
                    rule="VR701", path=rel, line=line, col=0,
                    message=f"exit path `{q}` is declared a `{name}` "
                            "release point (registry RESOURCE_PAIRS) "
                            "but no longer reaches any release "
                            "function — the resource leaks on this "
                            "path",
                    hint="release the resource on this path, or update "
                         "the registry if the lifecycle moved",
                    symbol=q, snippet=pf.line_text(line)))
        # (2) leak-on-raise after an acquire call
        acq_names = {q.split(".")[-1] for _rel, q in sets["acquire"]}
        rel_names = {q.split(".")[-1] for _rel, q in sets["release"]}
        lifecycle = sets["acquire"] | sets["release"]
        for pf in files:
            for q, info in pf.functions.items():
                if (pf.relpath, q) in lifecycle:
                    continue    # the lifecycle owners balance inline
                _LeakWalk(pf, q, info, name, acq_names, rel_names,
                          graph, reaching, out).run()


class _LeakWalk:
    """Statement-order walk: after an acquire call, a ``raise`` not
    covered by a release (direct call, handler/finally that reaches
    one, or an ownership transfer of the bound name) is a leak.
    Join-free and best-effort, like every other pass here."""

    def __init__(self, pf, q, info, resource, acq_names, rel_names,
                 graph, reaching, out):
        self.pf = pf
        self.q = q
        self.info = info
        self.resource = resource
        self.acq_names = acq_names
        self.rel_names = rel_names
        self.graph = graph
        self.reaching = reaching
        self.out = out
        self.pending: Optional[int] = None      # acquire line
        self.bound: Optional[str] = None
        self.covered_depth = 0
        self.emitted = False

    def _call_releases(self, node: ast.Call) -> bool:
        name = _final_name(node)
        if name in self.rel_names:
            return True
        chain = dotted_name(node.func)
        if chain is None:
            return False
        cls = self.info.cls
        return any(t in self.reaching for t in
                   self.graph.resolve(self.pf.relpath, cls, chain))

    def _scan_calls(self, node: ast.AST):
        """Updates pending/bound state from the expressions of one
        statement."""
        for sub in ast.walk(node):
            if not isinstance(sub, ast.Call):
                continue
            if self._call_releases(sub):
                self.pending = None
                self.bound = None
            elif _final_name(sub) in self.acq_names:
                self.pending = sub.lineno

    def run(self):
        if not any(n in self.pf.source for n in self.acq_names):
            return
        self._stmts(self.info.node.body)

    def _stmts(self, body):
        for stmt in body:
            self._stmt(stmt)

    def _stmt(self, stmt: ast.stmt):
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return
        if isinstance(stmt, ast.Try):
            covers = any(
                isinstance(sub, ast.Call) and self._call_releases(sub)
                for blk in ([h.body for h in stmt.handlers]
                            + [stmt.finalbody])
                for s in blk for sub in ast.walk(s))
            self.covered_depth += 1 if covers else 0
            self._stmts(stmt.body)
            self._stmts(stmt.orelse)
            self.covered_depth -= 1 if covers else 0
            for h in stmt.handlers:
                self._stmts(h.body)
            self._stmts(stmt.finalbody)
            return
        if isinstance(stmt, ast.Assign):
            self._scan_calls(stmt.value)
            was_acquire_value = any(
                isinstance(sub, ast.Call)
                and _final_name(sub) in self.acq_names
                for sub in ast.walk(stmt.value))
            if was_acquire_value and len(stmt.targets) == 1 \
                    and isinstance(stmt.targets[0], ast.Name):
                self.bound = stmt.targets[0].id
            elif self.bound is not None:
                # ownership transfer: the bound handle (or a value
                # derived from it) stored into an attribute/subscript
                uses_bound = any(
                    isinstance(sub, ast.Name) and sub.id == self.bound
                    for sub in ast.walk(stmt.value))
                if uses_bound and any(
                        isinstance(t, (ast.Attribute, ast.Subscript))
                        for t in stmt.targets):
                    self.pending = None
                    self.bound = None
            return
        if isinstance(stmt, ast.Return):
            self._scan_calls(stmt)
            self.pending = None
            self.bound = None
            return
        if isinstance(stmt, ast.Raise):
            if self.pending is not None and self.covered_depth == 0 \
                    and not self.emitted:
                self.emitted = True
                self.out.append(Finding(
                    rule="VR701", path=self.pf.relpath,
                    line=stmt.lineno, col=stmt.col_offset,
                    message=f"raise after acquiring `{self.resource}` "
                            f"(line {self.pending}) with no release or "
                            "ownership transfer on this path — the "
                            "resource leaks on this error exit",
                    hint="release in a try/finally (or an except path "
                         "that reaches the release), or transfer "
                         "ownership before raising",
                    symbol=self.q,
                    snippet=self.pf.line_text(stmt.lineno)))
            return
        if isinstance(stmt, (ast.If, ast.While)):
            self._scan_calls(stmt.test)
            self._stmts(stmt.body)
            self._stmts(stmt.orelse)
            return
        if isinstance(stmt, ast.For):
            self._scan_calls(stmt.iter)
            self._stmts(stmt.body)
            self._stmts(stmt.orelse)
            return
        if isinstance(stmt, ast.With):
            for item in stmt.items:
                self._scan_calls(item.context_expr)
            self._stmts(stmt.body)
            return
        for child in ast.iter_child_nodes(stmt):
            if isinstance(child, ast.expr):
                self._scan_calls(child)
            elif isinstance(child, ast.stmt):
                self._stmt(child)


# -- VR702: thread lifecycle -------------------------------------------------

def _vr702(files: List[ParsedFile], graph, out: List[Finding]):
    joined: Set[str] = set()
    daemoned: Set[str] = set()
    for s in graph.summaries.values():
        joined.update(s["joins"])
        daemoned.update(s["daemon_sets"])
    for pf in files:
        s = graph.summaries.get(pf.relpath)
        if s is None:
            continue
        for t in s["threads"]:
            if t["daemon"] is True:
                continue
            target = t.get("target")
            ok = target is not None and (target in joined
                                         or target in daemoned)
            if ok:
                continue
            what = "anonymous" if target is None else f"`{target}`"
            out.append(Finding(
                rule="VR702", path=pf.relpath, line=t["line"], col=0,
                message=f"non-daemon thread ({what}) is never joined "
                        "anywhere in the package and never marked "
                        "daemon — it outlives shutdown and blocks "
                        "interpreter exit",
                hint="pass daemon=True, or join it on a shutdown path "
                     "(stop()/close()/drain())",
                symbol=t.get("symbol", ""),
                snippet=pf.line_text(t["line"])))


# -- VR703: unclosed file/socket handles -------------------------------------

def _handle_call(pf: ParsedFile, node: ast.Call) -> bool:
    chain = dotted_name(node.func)
    if chain is None:
        return False
    resolved = pf.resolve_chain(chain)
    return resolved in _HANDLE_CALLS or chain in _HANDLE_CALLS


def _vr703_file(pf: ParsedFile, out: List[Finding]):
    if "open(" not in pf.source and "socket" not in pf.source:
        return
    parents: Dict[int, ast.AST] = {}
    for parent in ast.walk(pf.tree):
        for child in ast.iter_child_nodes(parent):
            parents[id(child)] = parent

    def enclosing_fn(node: ast.AST):
        best, span = None, None
        line = node.lineno
        for q, info in pf.functions.items():
            end = getattr(info.node, "end_lineno", info.node.lineno)
            if info.node.lineno <= line <= end:
                s = end - info.node.lineno
                if span is None or s < span:
                    best, span = (q, info), s
        return best

    def local_discharged(name: str, info) -> bool:
        """The bound handle is closed in a finally/except, returned,
        re-managed by ``with``/``closing``, or stored on an object."""
        for node in ast.walk(info.node):
            if isinstance(node, ast.Try):
                for blk in [h.body for h in node.handlers] \
                        + [node.finalbody]:
                    for s in blk:
                        for sub in ast.walk(s):
                            if isinstance(sub, ast.Call) \
                                    and isinstance(sub.func,
                                                   ast.Attribute) \
                                    and sub.func.attr == "close":
                                base = dotted_name(sub.func.value)
                                if base == name:
                                    return True
            if isinstance(node, ast.Return) and node.value is not None:
                for sub in ast.walk(node.value):
                    if isinstance(sub, ast.Name) and sub.id == name:
                        return True
            if isinstance(node, ast.With):
                for item in node.items:
                    for sub in ast.walk(item.context_expr):
                        if isinstance(sub, ast.Name) and sub.id == name:
                            return True
            if isinstance(node, ast.Call) and _final_name(node) \
                    in ("closing", "ExitStack", "enter_context"):
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Name) and sub.id == name:
                        return True
            if isinstance(node, ast.Assign):
                if any(isinstance(t, (ast.Attribute, ast.Subscript))
                       for t in node.targets) \
                        and any(isinstance(sub, ast.Name)
                                and sub.id == name
                                for sub in ast.walk(node.value)):
                    return True
        return False

    for node in ast.walk(pf.tree):
        if not isinstance(node, ast.Call) or not _handle_call(pf, node):
            continue
        parent = parents.get(id(node))
        # `with open(...)` (possibly through an `as` binding)
        if isinstance(parent, ast.withitem):
            continue
        enc = enclosing_fn(node)
        symbol = enc[0] if enc else ""
        if isinstance(parent, ast.Assign) and len(parent.targets) == 1:
            t = parent.targets[0]
            if isinstance(t, (ast.Attribute, ast.Subscript)):
                continue        # object-lifetime handle
            if isinstance(t, ast.Name) and enc is not None \
                    and local_discharged(t.id, enc[1]):
                continue
        elif isinstance(parent, ast.Return):
            continue            # ownership transferred to the caller
        elif isinstance(parent, ast.Call) and _final_name(parent) \
                in ("closing", "ExitStack", "enter_context"):
            continue
        out.append(Finding(
            rule="VR703", path=pf.relpath, line=node.lineno,
            col=node.col_offset,
            message="file/socket handle is neither managed by `with` "
                    "nor closed in a try/finally — it leaks on any "
                    "exception before the close",
            hint="use `with` (or contextlib.closing), or close in a "
                 "finally block",
            symbol=symbol, snippet=pf.line_text(node.lineno)))


# -- VR704: non-atomic writes on durability-critical paths -------------------

def _durable_functions(pf: ParsedFile):
    durable_module = any(
        pf.relpath == m or pf.relpath.endswith("/" + m)
        for m in DURABLE_WRITE_MODULES)
    for q, info in pf.functions.items():
        if durable_module \
                or info.node.lineno in pf.comments.durable_write:
            yield q, info


def _tmpish(node: ast.AST) -> bool:
    """The path expression visibly stages a temp name (`.tmp` literal,
    a ``tmp``-named variable, ``NamedTemporaryFile`` output, …)."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Constant) and isinstance(sub.value, str) \
                and "tmp" in sub.value.lower():
            return True
        if isinstance(sub, ast.Name) and "tmp" in sub.id.lower():
            return True
        if isinstance(sub, ast.Attribute) and "tmp" in sub.attr.lower():
            return True
    return False


def _vr704_file(pf: ParsedFile, out: List[Finding]):
    for q, info in _durable_functions(pf):
        has_rename = any(
            isinstance(sub, ast.Call)
            and _final_name(sub) in ("replace", "rename")
            for sub in ast.walk(info.node))
        # in-memory buffers (BytesIO staging before an atomic commit)
        # are not durable targets
        buffers: Set[str] = set()
        for sub in ast.walk(info.node):
            if isinstance(sub, ast.Assign) and len(sub.targets) == 1 \
                    and isinstance(sub.targets[0], ast.Name) \
                    and isinstance(sub.value, ast.Call) \
                    and _final_name(sub.value) in ("BytesIO",
                                                   "StringIO"):
                buffers.add(sub.targets[0].id)
        for node in ast.walk(info.node):
            if not isinstance(node, ast.Call):
                continue
            name = _final_name(node)
            mode = None
            if name in ("open", "ZipFile") and len(node.args) >= 2 \
                    and isinstance(node.args[1], ast.Constant):
                mode = node.args[1].value
            elif name in ("open", "ZipFile"):
                kw = next((k.value for k in node.keywords
                           if k.arg == "mode"), None)
                if isinstance(kw, ast.Constant):
                    mode = kw.value
            is_write = isinstance(mode, str) and mode[:1] in ("w", "x")
            chain = dotted_name(node.func)
            resolved = pf.resolve_chain(chain) if chain else ""
            if resolved.split(".")[0] == "numpy" \
                    and resolved.split(".")[-1] in (
                        "save", "savez", "savez_compressed") \
                    and node.args:
                is_write = True
            if not is_write:
                continue
            path_arg = node.args[0] if node.args else None
            if isinstance(path_arg, ast.Name) \
                    and path_arg.id in buffers:
                continue        # in-memory staging buffer
            if path_arg is not None and _tmpish(path_arg):
                continue        # staged write: the idiom's first half
            if has_rename:
                continue        # renamed into place in this function
            out.append(Finding(
                rule="VR704", path=pf.relpath, line=node.lineno,
                col=node.col_offset,
                message="durable write lands directly on its final "
                        "path — a crash mid-write leaves a torn file "
                        "a reader will trust",
                hint="stage to `<path>.tmp`, fsync, then os.replace() "
                     "into place (the export/snapshot idiom)",
                symbol=q, snippet=pf.line_text(node.lineno)))
