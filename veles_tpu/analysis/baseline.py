"""Baseline: accepted legacy findings, checked in next to the code.

The baseline is the migration valve every adopted-late linter needs:
run ``veles-tpu-lint --write-baseline`` once, commit the file, and from
then on CI fails only on NEW findings — the debt is visible (the file
is reviewable JSON) without blocking unrelated work.  Entries match by
:meth:`~veles_tpu.analysis.findings.Finding.fingerprint` (rule + path +
symbol + normalized source line), so editing a baselined line un-baselines
it on purpose.

The repo's own baseline lives at ``.veles-lint-baseline.json`` in the
repo root (found by walking up from the analyzed paths) and is EMPTY —
every finding the analyzer surfaced on the live package was fixed or
justified inline; keep it that way.
"""

from __future__ import annotations

import json
import os
from typing import Dict, Iterable, List, Optional

from .findings import Finding, sort_key

BASELINE_NAME = ".veles-lint-baseline.json"


def find_baseline(start: str) -> Optional[str]:
    """Walk up from ``start`` looking for the checked-in baseline."""
    d = os.path.abspath(start)
    if os.path.isfile(d):
        d = os.path.dirname(d)
    while True:
        cand = os.path.join(d, BASELINE_NAME)
        if os.path.isfile(cand):
            return cand
        parent = os.path.dirname(d)
        if parent == d:
            return None
        d = parent


def load_baseline(path: Optional[str]) -> Dict[str, dict]:
    """fingerprint -> entry.  A missing/None path is an empty baseline."""
    if not path or not os.path.isfile(path):
        return {}
    with open(path) as f:
        doc = json.load(f)
    entries = doc.get("findings", doc) if isinstance(doc, dict) else doc
    if not isinstance(entries, list):
        raise ValueError(f"{path}: baseline must hold a findings list")
    return {e["fingerprint"]: e for e in entries}


#: rules a baseline may never accept: VA003 (unparseable file — its
#: fingerprint has no symbol/snippet to go stale on, so accepting it
#: once would exclude the file from analysis forever) and VA002 (a
#: stale-entry report about the baseline itself — baselining it would
#: hide the staleness it reports).
NEVER_BASELINED = ("VA002", "VA003")


def entry_file_exists(path: str, base_dir: str) -> bool:
    """Does a baseline entry's file still exist?  Entry paths anchor at
    the enclosing package root's PARENT (engine._package_anchor), and
    the baseline usually sits at that anchor — but fixture trees put it
    inside the scanned directory, so the parent is tried too."""
    if not path:
        return False
    return any(os.path.isfile(os.path.join(d, path))
               for d in (base_dir, os.path.dirname(base_dir)))


def prune_missing(entries: Iterable[dict], base_dir: str) -> list:
    """Drop baseline entries whose file no longer exists."""
    return [e for e in entries
            if entry_file_exists(e.get("path", ""), base_dir)]


def write_baseline(path: str, findings: Iterable[Finding], *,
                   keep: Iterable[dict] = ()) -> int:
    """Rewrite the baseline from the given findings (plus ``keep``
    entries — prior accepted debt for files outside this scan, already
    pruned by the caller); returns the count.  Stable ordering +
    indented JSON so diffs of accepted debt review like code."""
    entries = [f.to_dict() for f in sorted(findings, key=sort_key)
               if f.rule not in NEVER_BASELINED]
    have = {e["fingerprint"] for e in entries}
    entries.extend(sorted(
        (e for e in keep if e.get("fingerprint") not in have),
        key=lambda e: (e.get("path", ""), e.get("line", 0))))
    doc = {"comment": "accepted legacy lint findings — see "
                      "docs/analysis.md for the baseline workflow",
           "findings": entries}
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=1)
        f.write("\n")
    os.replace(tmp, path)
    return len(entries)


def split_baselined(findings: Iterable[Finding],
                    baseline: Dict[str, dict]):
    """(new, accepted) partition of ``findings`` against the baseline.
    VA003 is always new — a file that does not parse was never
    analyzed, so no baseline may green it (see write_baseline)."""
    new: List[Finding] = []
    accepted: List[Finding] = []
    for f in findings:
        if f.rule not in NEVER_BASELINED and f.fingerprint() in baseline:
            accepted.append(f)
        else:
            new.append(f)
    return new, accepted
