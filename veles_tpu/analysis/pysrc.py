"""Parsed-file model shared by every rule: one ``ast.parse`` + one
``tokenize`` pass per file, an import-alias map for resolving dotted
call chains, and a function index with stable qualnames
(``Class.method``, ``outer.inner``).  Reachability closures live in
:mod:`~.callgraph`, which resolves references across module boundaries
(and, with ``cross_module=False``, reproduces the legacy module-local
reach)."""

from __future__ import annotations

import ast
import dataclasses
import os
from typing import Dict, List, Optional

from .comments import FileComments, scan_comments


def dotted_name(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for a pure Name/Attribute chain, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


@dataclasses.dataclass
class FnInfo:
    node: ast.AST           # FunctionDef | AsyncFunctionDef
    qualname: str
    cls: Optional[str]      # enclosing class name, for ``self.m()``


class ParsedFile:
    """Source + AST + comments + aliases for one .py file."""

    def __init__(self, path: str, relpath: str, source: str):
        self.path = path
        self.relpath = relpath.replace(os.sep, "/")
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        self.comments: FileComments = scan_comments(source)
        self.aliases = self._import_aliases(self.tree)
        self.functions: Dict[str, FnInfo] = {}
        self._index(self.tree, "", None)

    def line_text(self, line: int) -> str:
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1].strip()
        return ""

    # -- imports ------------------------------------------------------------
    @staticmethod
    def _import_aliases(tree: ast.AST) -> Dict[str, str]:
        """local name -> canonical dotted module (``jnp`` ->
        ``jax.numpy``, ``lax`` -> ``jax.lax``, ``np`` -> ``numpy``)."""
        out: Dict[str, str] = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    out[a.asname or a.name.split(".")[0]] = \
                        a.name if a.asname else a.name.split(".")[0]
            elif isinstance(node, ast.ImportFrom) and node.module:
                base = ("." * node.level) + node.module
                for a in node.names:
                    out[a.asname or a.name] = f"{base}.{a.name}"
        return out

    def resolve_chain(self, dotted: str) -> str:
        """Rewrite the chain's root through the alias map."""
        head, _, rest = dotted.partition(".")
        base = self.aliases.get(head, head)
        return f"{base}.{rest}" if rest else base

    # -- functions ----------------------------------------------------------
    def _index(self, node: ast.AST, prefix: str, cls: Optional[str]):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                q = f"{prefix}.{child.name}" if prefix else child.name
                self.functions[q] = FnInfo(child, q, cls)
                self._index(child, q, cls)
            elif isinstance(child, ast.ClassDef):
                cname = f"{prefix}.{child.name}" if prefix else child.name
                self._index(child, cname, child.name)
            else:
                self._index(child, prefix, cls)

    def module_functions(self) -> Dict[str, FnInfo]:
        return {q: i for q, i in self.functions.items() if "." not in q}


def parse_file(path: str, relpath: str) -> ParsedFile:
    with open(path, encoding="utf-8") as f:
        return ParsedFile(path, relpath, f.read())
