"""Parsed-file model shared by every rule: one ``ast.parse`` + one
``tokenize`` pass per file, an import-alias map for resolving dotted
call chains, and a function index with stable qualnames
(``Class.method``, ``outer.inner``)."""

from __future__ import annotations

import ast
import dataclasses
import os
from typing import Dict, List, Optional

from .comments import FileComments, scan_comments


def dotted_name(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for a pure Name/Attribute chain, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


@dataclasses.dataclass
class FnInfo:
    node: ast.AST           # FunctionDef | AsyncFunctionDef
    qualname: str
    cls: Optional[str]      # enclosing class name, for ``self.m()``


class ParsedFile:
    """Source + AST + comments + aliases for one .py file."""

    def __init__(self, path: str, relpath: str, source: str):
        self.path = path
        self.relpath = relpath.replace(os.sep, "/")
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        self.comments: FileComments = scan_comments(source)
        self.aliases = self._import_aliases(self.tree)
        self.functions: Dict[str, FnInfo] = {}
        self._index(self.tree, "", None)

    def line_text(self, line: int) -> str:
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1].strip()
        return ""

    # -- imports ------------------------------------------------------------
    @staticmethod
    def _import_aliases(tree: ast.AST) -> Dict[str, str]:
        """local name -> canonical dotted module (``jnp`` ->
        ``jax.numpy``, ``lax`` -> ``jax.lax``, ``np`` -> ``numpy``)."""
        out: Dict[str, str] = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    out[a.asname or a.name.split(".")[0]] = \
                        a.name if a.asname else a.name.split(".")[0]
            elif isinstance(node, ast.ImportFrom) and node.module:
                base = ("." * node.level) + node.module
                for a in node.names:
                    out[a.asname or a.name] = f"{base}.{a.name}"
        return out

    def resolve_chain(self, dotted: str) -> str:
        """Rewrite the chain's root through the alias map."""
        head, _, rest = dotted.partition(".")
        base = self.aliases.get(head, head)
        return f"{base}.{rest}" if rest else base

    # -- functions ----------------------------------------------------------
    def _index(self, node: ast.AST, prefix: str, cls: Optional[str]):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                q = f"{prefix}.{child.name}" if prefix else child.name
                self.functions[q] = FnInfo(child, q, cls)
                self._index(child, q, cls)
            elif isinstance(child, ast.ClassDef):
                cname = f"{prefix}.{child.name}" if prefix else child.name
                self._index(child, cname, child.name)
            else:
                self._index(child, prefix, cls)

    def module_functions(self) -> Dict[str, FnInfo]:
        return {q: i for q, i in self.functions.items() if "." not in q}


def call_targets(pf: ParsedFile, info: FnInfo):
    """Module-local qualnames the function's body references: bare
    ``Name`` uses of module-level functions (calls, and references
    passed as callbacks) and ``self.<method>`` of the same class.  The
    shared closure machinery of the trace (VT1xx), sharding (VS5xx),
    recompile (VP6xx) and lock-graph (VC204/205) rules — all of them
    deliberately module-local, never whole-program."""
    mod_fns = pf.module_functions()
    out = set()
    for node in ast.walk(info.node):
        if isinstance(node, ast.Name) and node.id in mod_fns:
            out.add(node.id)
        elif isinstance(node, ast.Attribute) and info.cls \
                and isinstance(node.value, ast.Name) \
                and node.value.id == "self":
            cand = f"{info.cls}.{node.attr}"
            if cand in pf.functions:
                out.add(cand)
    return out


def local_closure(pf: ParsedFile, roots) -> set:
    """Roots + nested ``def``s + transitively-called module-local
    functions (see :func:`call_targets`), restricted to qualnames that
    exist in the file."""
    seen = {q for q in roots if q in pf.functions}
    work = list(seen)
    while work:
        q = work.pop()
        for q2 in pf.functions:
            if q2.startswith(q + ".") and q2 not in seen:
                seen.add(q2)
                work.append(q2)
        for q2 in call_targets(pf, pf.functions[q]):
            if q2 not in seen:
                seen.add(q2)
                work.append(q2)
    return seen


def parse_file(path: str, relpath: str) -> ParsedFile:
    with open(path, encoding="utf-8") as f:
        return ParsedFile(path, relpath, f.read())
