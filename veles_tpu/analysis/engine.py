"""Analysis driver: file discovery -> summaries/graph -> rules ->
suppressions -> baseline.

Everything here is pure stdlib and never imports the modules it
analyzes; ``run_analysis`` is the programmatic entry the CLI and the
tier-1 self-check test (tests/test_analysis.py) share.  Since the
whole-package resolution layer landed, every run builds (or loads from
the summary cache) a :class:`~.callgraph.PackageGraph` first: the
closure-based families (VT1xx, VC204/VC205, VS5xx, VP6xx, VR7xx)
consume package-wide scope/lock/lifecycle facts from it, while the
per-file syntactic rules still walk each analyzed file's AST.

Caching (``.veles-lint-cache.json``, gitignored):

* **summaries** key on each file's content hash — an edit invalidates
  exactly that file's summary, nothing else;
* a **findings memo** keys on the digest of every (path, hash) pair
  plus the docs and analyzer digests — a warm unchanged re-run skips
  parsing entirely, and ``--changed`` parses only the changed files
  while the closure reads everyone else's summary from the cache.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Tuple

from . import (concurrency_rules, config_rules, metrics_rules,
               recompile_rules, resource_rules, sharding_rules,
               trace_rules)
from .baseline import (entry_file_exists, find_baseline, load_baseline,
                       split_baselined)
from .callgraph import (CACHE_NAME, PackageGraph, SummaryCache,
                        content_hash, docs_digest, summarize)
from .findings import Finding, sort_key
from .pysrc import ParsedFile, parse_file
import hashlib

_SKIP_DIRS = {"__pycache__", ".git", ".venv", "node_modules"}


def _package_anchor(directory: str) -> str:
    """Walk up past ``__init__.py`` packages: the anchor display paths
    are computed against.  ``.../veles_tpu/runtime`` anchors at
    ``.../`` (the repo root), so `veles-tpu-lint veles_tpu` and
    `veles-tpu-lint veles_tpu/runtime/engine.py` both display
    ``veles_tpu/runtime/engine.py`` and baseline fingerprints agree
    across invocation styles, machines, and working directories."""
    d = os.path.abspath(directory)
    while os.path.isfile(os.path.join(d, "__init__.py")):
        parent = os.path.dirname(d)
        if parent == d:
            break
        d = parent
    return d


def iter_python_files(paths) -> List[Tuple[str, str]]:
    """(abspath, display-relpath) for every .py under ``paths`` (files
    or directories), stable order.  Display paths anchor at the
    enclosing package root's parent (:func:`_package_anchor`), never at
    the invoker's cwd."""
    out: List[Tuple[str, str]] = []
    seen = set()
    for path in paths:
        path = os.path.abspath(path)
        if os.path.isfile(path):
            anchor = _package_anchor(os.path.dirname(path))
            if path not in seen:
                seen.add(path)
                out.append((path, os.path.relpath(path, anchor)))
            continue
        anchor = _package_anchor(path.rstrip(os.sep))
        if anchor == path.rstrip(os.sep):   # not a package: its parent
            anchor = os.path.dirname(path.rstrip(os.sep)) or path
        for base, dirs, files in os.walk(path):
            dirs[:] = sorted(d for d in dirs
                             if d not in _SKIP_DIRS
                             and not d.startswith("."))
            for fn in sorted(files):
                full = os.path.join(base, fn)
                if fn.endswith(".py") and full not in seen:
                    seen.add(full)
                    out.append((full, os.path.relpath(full, anchor)))
    return out


def _parse_all(file_list: List[Tuple[str, str]],
               blobs: Optional[Dict[str, bytes]] = None):
    """(parsed files, VA003 findings for the ones that do not).

    With ``blobs`` (the cached path), files parse from the bytes the
    caller already read and hashed — the summary cache must key each
    summary by the hash of the EXACT content it was built from, so a
    second read racing an editor save could poison it."""
    parsed: List[ParsedFile] = []
    findings: List[Finding] = []
    for full, rel in file_list:
        try:
            if blobs is not None:
                if rel not in blobs:
                    continue            # unreadable at hash time
                pf = ParsedFile(full, rel, blobs[rel].decode("utf-8"))
            else:
                pf = parse_file(full, rel)
        except (SyntaxError, UnicodeDecodeError) as e:
            findings.append(Finding(
                rule="VA003", path=rel.replace(os.sep, "/"),
                line=getattr(e, "lineno", 1) or 1, col=0,
                message=f"file does not parse: "
                        f"{e.msg if hasattr(e, 'msg') else e}",
                hint="the analyzer needs valid Python"))
            continue
        parsed.append(pf)
    return parsed, findings


def _run_rules(parsed: List[ParsedFile], va_findings: List[Finding],
               graph: PackageGraph, *,
               trace_roots: Optional[dict],
               docs_dir: Optional[str],
               package_scan: Optional[bool]) -> List[Finding]:
    """All rules over already-parsed files + a ready graph; returns
    findings AFTER inline suppressions, BEFORE the baseline."""
    findings = list(va_findings)
    by_path: Dict[str, ParsedFile] = {pf.relpath: pf for pf in parsed}

    tscope: Dict[str, Dict[str, bool]] = {}
    for (rel, q), tainted in graph.traced_scope(trace_roots).items():
        tscope.setdefault(rel, {})[q] = tainted

    for pf in parsed:
        findings.extend(trace_rules.check(pf, tscope.get(pf.relpath, {})))
        findings.extend(concurrency_rules.check(pf))
        for sup in pf.comments.suppressions.values():
            if not sup.reason:
                findings.append(Finding(
                    rule="VA001", path=pf.relpath,
                    line=sup.comment_line, col=0,
                    message="suppression without a reason — the "
                            "justification is part of the syntax "
                            "(`# lint: disable=RULE why`)",
                    hint="say why the finding is acceptable",
                    snippet=pf.line_text(sup.comment_line)))
    findings.extend(
        concurrency_rules.check_lock_graph_package(graph, parsed))
    findings.extend(config_rules.check(parsed, docs_dir,
                                       package_scan=package_scan))
    findings.extend(metrics_rules.check(parsed, docs_dir,
                                        package_scan=package_scan))
    findings.extend(sharding_rules.check(parsed, graph))
    findings.extend(recompile_rules.check(parsed, graph))
    findings.extend(resource_rules.check(parsed, graph,
                                         package_scan=package_scan))

    kept: List[Finding] = []
    for f in findings:
        pf = by_path.get(f.path)
        if pf is not None and f.rule != "VA001" \
                and pf.comments.suppressed(f.line, f.rule):
            continue
        kept.append(f)
    kept.sort(key=sort_key)
    return kept


def analyze_files(file_list: List[Tuple[str, str]], *,
                  trace_roots: Optional[Dict[str, Dict[str, str]]] = None,
                  docs_dir: Optional[str] = None,
                  package_scan: Optional[bool] = None,
                  cross_module: bool = True) -> List[Finding]:
    """Run every rule over the files; returns findings AFTER inline
    suppressions (``# lint: disable=``) but BEFORE the baseline.

    ``package_scan`` gates the whole-inventory rules (VK302/VK303 dead/
    undocumented config keys, VM402 ghost metrics, VR702 never-joined
    threads): they can only prove "nowhere" against a full package, so
    a subset scan (``--changed``, a single file) must not fire them.
    ``cross_module=False`` restricts every closure to the legacy
    module-local reach (the pre-graph analyzer — the mode the
    blind-spot regression tests pin).  The graph here covers exactly
    ``file_list``; :func:`run_analysis` is the entry that widens it
    with cached summaries for ``--changed`` scans."""
    parsed, va_findings = _parse_all(file_list)
    graph = PackageGraph({pf.relpath: summarize(pf) for pf in parsed},
                         cross_module=cross_module)
    return _run_rules(parsed, va_findings, graph,
                      trace_roots=trace_roots, docs_dir=docs_dir,
                      package_scan=package_scan)


def _auto_docs_dir(paths) -> Optional[str]:
    for path in paths:
        d = os.path.abspath(path)
        if os.path.isfile(d):
            d = os.path.dirname(d)
        while True:
            cand = os.path.join(d, "docs")
            if os.path.isdir(cand):
                return cand
            parent = os.path.dirname(d)
            if parent == d:
                break
            d = parent
    return None


def _auto_cache_path(paths, baseline_path: Optional[str]) -> Optional[str]:
    """The summary cache sits next to the baseline (repo root); with no
    baseline, next to the first analyzed package's anchor."""
    if baseline_path:
        return os.path.join(os.path.dirname(os.path.abspath(
            baseline_path)), CACHE_NAME)
    for path in paths:
        d = os.path.abspath(path)
        if os.path.isfile(d):
            d = os.path.dirname(d)
        if os.path.isdir(d):
            return os.path.join(_package_anchor(d), CACHE_NAME)
    return None


def run_analysis(paths, *, baseline_path: Optional[str] = "auto",
                 docs_dir: Optional[str] = "auto",
                 trace_roots: Optional[dict] = None,
                 cache_path: Optional[str] = "auto",
                 scope_paths: Optional[list] = None,
                 cross_module: bool = True) -> dict:
    """Full pipeline; returns::

        {"findings": [new Finding...], "accepted": [baselined...],
         "all": [...], "files": N, "baseline_path": path_or_None}

    ``scope_paths`` (the ``--changed`` shape) widens the *graph* beyond
    the analyzed ``paths``: every Python file under it contributes a
    summary (from the cache when its content hash matches, else a
    fresh parse) so cross-module closures stay package-accurate while
    rules run — and findings are emitted — only for ``paths``.
    """
    file_list = iter_python_files(paths)
    if docs_dir == "auto":
        docs_dir = _auto_docs_dir(paths)
    if baseline_path == "auto":
        baseline_path = find_baseline(
            os.path.abspath(paths[0])) if paths else None
    # whole-inventory rules need a whole package: true only when some
    # PATH argument is a package directory (never for --changed /
    # single-file scans, whose file list may happen to include an
    # __init__.py without covering the package)
    package_scan = any(
        os.path.isdir(p)
        and os.path.isfile(os.path.join(p, "__init__.py"))
        for p in paths)

    cache = None
    if cache_path == "auto":
        cache_path = _auto_cache_path(
            list(scope_paths or ()) + list(paths), baseline_path)
    if cache_path and trace_roots is None and cross_module:
        cache = SummaryCache(cache_path)

    scope_list = list(file_list)
    if scope_paths:
        seen = {full for full, _rel in scope_list}
        for full, rel in iter_python_files(scope_paths):
            if full not in seen:
                seen.add(full)
                scope_list.append((full, rel))

    all_findings: Optional[List[Finding]] = None
    if cache is not None or (scope_paths and cross_module
                             and trace_roots is None):
        # hash everything in graph scope; the analyzed subset + flags
        # key the findings memo
        hashes: Dict[str, str] = {}
        blobs: Dict[str, bytes] = {}
        for full, rel in scope_list:
            try:
                with open(full, "rb") as f:
                    data = f.read()
            except OSError:
                continue
            hashes[rel] = content_hash(data)
            blobs[rel] = data
        memo = None
        context = None
        if cache is not None:
            ddig = docs_digest(docs_dir)
            h = hashlib.sha256(ddig.encode())
            h.update(repr(sorted(rel
                                 for _f, rel in file_list)).encode())
            h.update(repr(bool(package_scan)).encode())
            context = cache.context_digest(hashes, h.hexdigest()[:16])
            memo = cache.memo(context)
        if memo is not None:
            all_findings = [_revive(d) for d in memo]
        else:
            analyzed = {rel for _full, rel in file_list}
            parsed, va_findings = _parse_all(file_list, blobs)
            summaries = {pf.relpath: summarize(pf) for pf in parsed}
            if cache is not None:
                for pf in parsed:
                    cache.put_summary(pf.relpath,
                                      hashes.get(pf.relpath, ""),
                                      summaries[pf.relpath])
            for full, rel in scope_list:
                if rel in analyzed or rel not in hashes:
                    continue
                summary = cache.summary(rel, hashes[rel]) \
                    if cache is not None else None
                if summary is None:
                    try:
                        pf = ParsedFile(full, rel,
                                        blobs[rel].decode("utf-8"))
                        summary = summarize(pf)
                    except (SyntaxError, UnicodeDecodeError,
                            ValueError):
                        continue    # out-of-scan broken file: no edges
                    if cache is not None:
                        cache.put_summary(rel, hashes[rel], summary)
                summaries.setdefault(rel, summary)
            graph = PackageGraph(summaries, cross_module=True)
            all_findings = _run_rules(
                parsed, va_findings, graph, trace_roots=None,
                docs_dir=docs_dir, package_scan=package_scan)
            if cache is not None:
                cache.put_memo(context,
                               [f.to_dict() for f in all_findings])
        if cache is not None:
            cache.save()
    if all_findings is None:
        all_findings = analyze_files(
            file_list, trace_roots=trace_roots, docs_dir=docs_dir,
            package_scan=package_scan, cross_module=cross_module)

    baseline = load_baseline(baseline_path)
    new, accepted = split_baselined(all_findings, baseline)
    new.extend(_stale_baseline_findings(baseline, baseline_path,
                                        file_list, accepted))
    new.sort(key=sort_key)
    return {"findings": new, "accepted": accepted, "all": all_findings,
            "files": len(file_list), "baseline_path": baseline_path,
            "docs_dir": docs_dir}


def _revive(d: dict) -> Finding:
    return Finding(rule=d["rule"], path=d["path"], line=d["line"],
                   col=d["col"], message=d["message"],
                   hint=d.get("hint", ""), symbol=d.get("symbol", ""),
                   snippet=d.get("snippet", ""))


def _stale_baseline_findings(baseline, baseline_path, file_list,
                             accepted):
    """VA002 (warning) for baseline entries nothing matches anymore:
    either the entry's file was scanned and the finding is gone (fixed
    — the debt record lingers), or the file itself no longer exists.
    Entries for files outside a subset scan are left alone — a
    one-file pre-commit run cannot judge the rest of the baseline."""
    if not baseline:
        return []
    matched = {f.fingerprint() for f in accepted}
    scanned = {rel.replace(os.sep, "/") for _full, rel in file_list}
    base_dir = os.path.dirname(os.path.abspath(baseline_path)) \
        if baseline_path else os.getcwd()
    bl_name = os.path.basename(baseline_path) if baseline_path \
        else "baseline"
    out = []
    for fp, entry in sorted(baseline.items()):
        if fp in matched:
            continue
        path = entry.get("path", "?")
        exists = path in scanned \
            or entry_file_exists(path, base_dir)
        if path in scanned or not exists:
            out.append(Finding(
                rule="VA002", path=path,
                line=int(entry.get("line", 1) or 1), col=0,
                message=f"stale baseline entry ({entry.get('rule', '?')}"
                        f" {fp}): the finding it accepted no longer "
                        "exists" + ("" if exists
                                    else " (file is gone)"),
                hint=f"run --write-baseline to prune {bl_name}",
                symbol=entry.get("symbol", ""),
                snippet=entry.get("snippet", "")))
    return out
