"""Analysis driver: file discovery -> rules -> suppressions -> baseline.

Everything here is pure stdlib and never imports the modules it
analyzes; ``run_analysis`` is the programmatic entry the CLI and the
tier-1 self-check test (tests/test_analysis.py) share.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Tuple

from . import (concurrency_rules, config_rules, metrics_rules,
               recompile_rules, sharding_rules, trace_rules)
from .baseline import (entry_file_exists, find_baseline, load_baseline,
                       split_baselined)
from .findings import Finding, sort_key
from .pysrc import ParsedFile, parse_file

_SKIP_DIRS = {"__pycache__", ".git", ".venv", "node_modules"}


def _package_anchor(directory: str) -> str:
    """Walk up past ``__init__.py`` packages: the anchor display paths
    are computed against.  ``.../veles_tpu/runtime`` anchors at
    ``.../`` (the repo root), so `veles-tpu-lint veles_tpu` and
    `veles-tpu-lint veles_tpu/runtime/engine.py` both display
    ``veles_tpu/runtime/engine.py`` and baseline fingerprints agree
    across invocation styles, machines, and working directories."""
    d = os.path.abspath(directory)
    while os.path.isfile(os.path.join(d, "__init__.py")):
        parent = os.path.dirname(d)
        if parent == d:
            break
        d = parent
    return d


def iter_python_files(paths) -> List[Tuple[str, str]]:
    """(abspath, display-relpath) for every .py under ``paths`` (files
    or directories), stable order.  Display paths anchor at the
    enclosing package root's parent (:func:`_package_anchor`), never at
    the invoker's cwd."""
    out: List[Tuple[str, str]] = []
    seen = set()
    for path in paths:
        path = os.path.abspath(path)
        if os.path.isfile(path):
            anchor = _package_anchor(os.path.dirname(path))
            if path not in seen:
                seen.add(path)
                out.append((path, os.path.relpath(path, anchor)))
            continue
        anchor = _package_anchor(path.rstrip(os.sep))
        if anchor == path.rstrip(os.sep):   # not a package: its parent
            anchor = os.path.dirname(path.rstrip(os.sep)) or path
        for base, dirs, files in os.walk(path):
            dirs[:] = sorted(d for d in dirs
                             if d not in _SKIP_DIRS
                             and not d.startswith("."))
            for fn in sorted(files):
                full = os.path.join(base, fn)
                if fn.endswith(".py") and full not in seen:
                    seen.add(full)
                    out.append((full, os.path.relpath(full, anchor)))
    return out


def analyze_files(file_list: List[Tuple[str, str]], *,
                  trace_roots: Optional[Dict[str, Dict[str, str]]] = None,
                  docs_dir: Optional[str] = None,
                  package_scan: Optional[bool] = None) -> List[Finding]:
    """Run every rule over the files; returns findings AFTER inline
    suppressions (``# lint: disable=``) but BEFORE the baseline.

    ``package_scan`` gates the whole-inventory rules (VK302/VK303 dead/
    undocumented config keys, VM402 ghost metrics): they can only prove
    "nowhere" against a full package, so a subset scan (``--changed``,
    a single file) must not fire them.  ``None`` keeps each rule's own
    legacy inference; :func:`run_analysis` passes the real answer —
    whether any analyzed PATH argument was a package directory."""
    parsed: List[ParsedFile] = []
    findings: List[Finding] = []
    by_path: Dict[str, ParsedFile] = {}
    for full, rel in file_list:
        try:
            pf = parse_file(full, rel)
        except (SyntaxError, UnicodeDecodeError) as e:
            findings.append(Finding(
                rule="VA003", path=rel.replace(os.sep, "/"),
                line=getattr(e, "lineno", 1) or 1, col=0,
                message=f"file does not parse: {e.msg if hasattr(e, 'msg') else e}",
                hint="the analyzer needs valid Python"))
            continue
        parsed.append(pf)
        by_path[pf.relpath] = pf

    for pf in parsed:
        findings.extend(trace_rules.check(pf, trace_roots))
        findings.extend(concurrency_rules.check(pf))
        for sup in pf.comments.suppressions.values():
            if not sup.reason:
                findings.append(Finding(
                    rule="VA001", path=pf.relpath,
                    line=sup.comment_line, col=0,
                    message="suppression without a reason — the "
                            "justification is part of the syntax "
                            "(`# lint: disable=RULE why`)",
                    hint="say why the finding is acceptable",
                    snippet=pf.line_text(sup.comment_line)))
    findings.extend(config_rules.check(parsed, docs_dir,
                                       package_scan=package_scan))
    findings.extend(metrics_rules.check(parsed, docs_dir,
                                        package_scan=package_scan))
    findings.extend(sharding_rules.check(parsed))
    findings.extend(recompile_rules.check(parsed))

    kept: List[Finding] = []
    for f in findings:
        pf = by_path.get(f.path)
        if pf is not None and f.rule != "VA001" \
                and pf.comments.suppressed(f.line, f.rule):
            continue
        kept.append(f)
    kept.sort(key=sort_key)
    return kept


def _auto_docs_dir(paths) -> Optional[str]:
    for path in paths:
        d = os.path.abspath(path)
        if os.path.isfile(d):
            d = os.path.dirname(d)
        while True:
            cand = os.path.join(d, "docs")
            if os.path.isdir(cand):
                return cand
            parent = os.path.dirname(d)
            if parent == d:
                break
            d = parent
    return None


def run_analysis(paths, *, baseline_path: Optional[str] = "auto",
                 docs_dir: Optional[str] = "auto",
                 trace_roots: Optional[dict] = None) -> dict:
    """Full pipeline; returns::

        {"findings": [new Finding...], "accepted": [baselined...],
         "all": [...], "files": N, "baseline_path": path_or_None}
    """
    file_list = iter_python_files(paths)
    if docs_dir == "auto":
        docs_dir = _auto_docs_dir(paths)
    if baseline_path == "auto":
        baseline_path = find_baseline(
            os.path.abspath(paths[0])) if paths else None
    # whole-inventory rules need a whole package: true only when some
    # PATH argument is a package directory (never for --changed /
    # single-file scans, whose file list may happen to include an
    # __init__.py without covering the package)
    package_scan = any(
        os.path.isdir(p)
        and os.path.isfile(os.path.join(p, "__init__.py"))
        for p in paths)
    all_findings = analyze_files(file_list, trace_roots=trace_roots,
                                 docs_dir=docs_dir,
                                 package_scan=package_scan)
    baseline = load_baseline(baseline_path)
    new, accepted = split_baselined(all_findings, baseline)
    new.extend(_stale_baseline_findings(baseline, baseline_path,
                                        file_list, accepted))
    new.sort(key=sort_key)
    return {"findings": new, "accepted": accepted, "all": all_findings,
            "files": len(file_list), "baseline_path": baseline_path,
            "docs_dir": docs_dir}


def _stale_baseline_findings(baseline, baseline_path, file_list,
                             accepted):
    """VA002 (warning) for baseline entries nothing matches anymore:
    either the entry's file was scanned and the finding is gone (fixed
    — the debt record lingers), or the file itself no longer exists.
    Entries for files outside a subset scan are left alone — a
    one-file pre-commit run cannot judge the rest of the baseline."""
    if not baseline:
        return []
    matched = {f.fingerprint() for f in accepted}
    scanned = {rel.replace(os.sep, "/") for _full, rel in file_list}
    base_dir = os.path.dirname(os.path.abspath(baseline_path)) \
        if baseline_path else os.getcwd()
    bl_name = os.path.basename(baseline_path) if baseline_path \
        else "baseline"
    out = []
    for fp, entry in sorted(baseline.items()):
        if fp in matched:
            continue
        path = entry.get("path", "?")
        exists = path in scanned \
            or entry_file_exists(path, base_dir)
        if path in scanned or not exists:
            out.append(Finding(
                rule="VA002", path=path,
                line=int(entry.get("line", 1) or 1), col=0,
                message=f"stale baseline entry ({entry.get('rule', '?')}"
                        f" {fp}): the finding it accepted no longer "
                        "exists" + ("" if exists
                                    else " (file is gone)"),
                hint=f"run --write-baseline to prune {bl_name}",
                symbol=entry.get("symbol", ""),
                snippet=entry.get("snippet", "")))
    return out
