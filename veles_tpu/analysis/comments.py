"""Comment-level syntax: suppressions and lock annotations.

The analyzer's three in-source annotations all live in comments, so one
``tokenize`` pass per file collects them (``ast`` drops comments):

``# lint: disable=VT101[,VC201] <reason>``
    Suppress those rules on this line — or, when the comment is a
    standalone line, on the next code line.  The reason is REQUIRED:
    a reasonless suppression still suppresses, but emits VA001 so the
    missing justification is itself a finding.

``# guarded-by: self._lock``
    On a field assignment (``self.x = ... # guarded-by: self._lock``):
    every read/write of ``self.x`` elsewhere in the class must sit
    inside ``with self._lock:`` (concurrency_rules, VC201).

``# requires-lock: self._lock``
    On a ``def`` line: the method's contract is "caller holds the
    lock", so its body counts as guarded without its own ``with``.

``# not-shared: <reason>``
    On a ``def`` line: the method runs before the object is visible to
    other threads (construction helpers ``__init__`` delegates to), so
    VC201 does not apply inside it.  The reason is required, like a
    suppression's.

``# trace-root: traced|builder``
    On a ``def`` line: mark the function a trace root without a
    registry entry — the escape hatch for modules the registry does not
    know (and the fixture syntax the analyzer's own tests use).

``# shard-map-root: axis[,axis...]``
    On a ``def`` line: the function's body runs under ``shard_map`` (or
    a schedule's manual-axes scope) with the named mesh axes bound —
    raw collectives (``psum``/``ppermute``/…) are legal inside it
    (sharding_rules, VS502) and literal axis names are checked against
    the listed environment (VS501).  The registry's ``SHARD_MAP_ROOTS``
    is the checked-in form; the comment is the fixture/escape syntax.

``# host-loop-root:``
    On a ``def`` line: the function is a hot host loop (scheduler tick,
    REST request handler) — traced-program builders reachable from it
    must route through ``StepCache`` (recompile_rules, VP603).

``# resource-acquire: <name>`` / ``# resource-release: <name>``
    On a ``def`` line: the function acquires / releases the named
    resource (pages, handles, …).  The VR701 lifecycle rule pairs the
    two over the package call graph; the registry's
    ``RESOURCE_PAIRS`` is the checked-in form, the comment the
    fixture/escape syntax (resource_rules).

``# durable-write:``
    On a ``def`` line: the function's file writes must follow the
    tmp-fsync-rename idiom (resource_rules, VR704) — the fixture form
    of the registry's ``DURABLE_WRITE_MODULES``.
"""

from __future__ import annotations

import dataclasses
import io
import re
import tokenize
from typing import Dict, List, Optional, Set, Tuple

_DISABLE_RE = re.compile(
    r"#\s*lint:\s*disable=([A-Z]{2}\d{3}(?:\s*,\s*[A-Z]{2}\d{3})*)"
    r"\s*(.*)")
_GUARDED_RE = re.compile(r"#\s*guarded-by:\s*([A-Za-z_][\w.]*)")
_REQUIRES_RE = re.compile(r"#\s*requires-lock:\s*([A-Za-z_][\w.]*)")
_TRACEROOT_RE = re.compile(r"#\s*trace-root:\s*(traced|builder)")
_NOTSHARED_RE = re.compile(r"#\s*not-shared:\s*(\S.*)")
_SHARDROOT_RE = re.compile(
    r"#\s*shard-map-root:\s*([A-Za-z_]\w*(?:\s*,\s*[A-Za-z_]\w*)*)")
_HOSTLOOP_RE = re.compile(r"#\s*host-loop-root:")
_RES_ACQ_RE = re.compile(r"#\s*resource-acquire:\s*([\w-]+)")
_RES_REL_RE = re.compile(r"#\s*resource-release:\s*([\w-]+)")
_DURABLE_RE = re.compile(r"#\s*durable-write:")


@dataclasses.dataclass
class Suppression:
    line: int                 # line the suppression APPLIES to
    rules: Set[str]
    reason: str
    comment_line: int         # line the comment itself sits on


@dataclasses.dataclass
class FileComments:
    #: applies-to line -> suppression
    suppressions: Dict[int, Suppression]
    #: comment line -> lock expression text (``self._lock``)
    guarded_by: Dict[int, str]
    #: comment line -> lock expression text
    requires_lock: Dict[int, str]
    #: comment line -> "traced" | "builder"
    trace_root: Dict[int, str]
    #: comment line -> reason the method is construction-only
    not_shared: Dict[int, str]
    #: comment line -> tuple of mesh axes bound in the shard_map body
    shard_map_root: Dict[int, Tuple[str, ...]]
    #: comment lines marked as host hot loops (VP603 roots)
    host_loop_root: Set[int]
    #: comment line -> resource name acquired / released (VR701)
    resource_acquire: Dict[int, str]
    resource_release: Dict[int, str]
    #: comment lines whose function must write atomically (VR704)
    durable_write: Set[int]

    def suppressed(self, line: int, rule: str) -> Optional[Suppression]:
        s = self.suppressions.get(line)
        if s is not None and rule in s.rules:
            return s
        return None


def scan_comments(source: str) -> FileComments:
    """One tokenize pass: every comment, its line, and whether any code
    shares that line (standalone comments bind to the NEXT code line)."""
    comments: List[Tuple[int, int, str]] = []   # (line, col, text)
    code_lines: Set[int] = set()
    try:
        toks = list(tokenize.generate_tokens(
            io.StringIO(source).readline))
    except (tokenize.TokenError, IndentationError, SyntaxError):
        toks = []
    for tok in toks:
        if tok.type == tokenize.COMMENT:
            comments.append((tok.start[0], tok.start[1], tok.string))
        elif tok.type not in (tokenize.NL, tokenize.NEWLINE,
                              tokenize.INDENT, tokenize.DEDENT,
                              tokenize.ENCODING, tokenize.ENDMARKER):
            for ln in range(tok.start[0], tok.end[0] + 1):
                code_lines.add(ln)

    out = FileComments({}, {}, {}, {}, {}, {}, set(), {}, {}, set())
    n_lines = source.count("\n") + 1
    for line, _col, text in comments:
        m = _DISABLE_RE.search(text)
        if m:
            rules = {r.strip() for r in m.group(1).split(",")}
            reason = m.group(2).strip()
            target = line
            if line not in code_lines:
                # standalone comment: binds to the next code line
                target = line + 1
                while target <= n_lines and target not in code_lines:
                    target += 1
            prev = out.suppressions.get(target)
            if prev is not None:
                prev.rules |= rules
                prev.reason = prev.reason or reason
            else:
                out.suppressions[target] = Suppression(
                    target, rules, reason, line)
        m = _GUARDED_RE.search(text)
        if m:
            out.guarded_by[line] = m.group(1)
        m = _REQUIRES_RE.search(text)
        if m:
            out.requires_lock[line] = m.group(1)
        m = _TRACEROOT_RE.search(text)
        if m:
            out.trace_root[line] = m.group(1)
        m = _NOTSHARED_RE.search(text)
        if m:
            out.not_shared[line] = m.group(1)
        m = _SHARDROOT_RE.search(text)
        if m:
            out.shard_map_root[line] = tuple(
                a.strip() for a in m.group(1).split(","))
        if _HOSTLOOP_RE.search(text):
            out.host_loop_root.add(line)
        m = _RES_ACQ_RE.search(text)
        if m:
            out.resource_acquire[line] = m.group(1)
        m = _RES_REL_RE.search(text)
        if m:
            out.resource_release[line] = m.group(1)
        if _DURABLE_RE.search(text):
            out.durable_write.add(line)
    return out
