"""VC2xx — host-concurrency discipline.

The host side of this runtime is deliberately multi-threaded: the
decode-engine scheduler, REST worker threads, the deploy control plane,
the snapshot watcher and the status reporter all share mutable state.
The locking convention is documented per field with a trailing
``# guarded-by: <lock>`` comment on the field's defining assignment
(``self._queue = deque()  # guarded-by: self._qlock``, or a module
global guarded by a module-level lock), and this rule makes the
convention checkable:

VC201  a read or write of a guarded field outside a ``with <lock>:``
       block in the same function.  ``__init__`` of the defining class
       is exempt (construction precedes sharing), as is module-level
       initialization; a method whose contract is "caller holds the
       lock" declares it with ``# requires-lock: <lock>`` on its
       ``def`` line.
VC202  ``lock.acquire()`` without an enclosing/immediately-following
       ``try/finally: lock.release()`` — an exception between acquire
       and release deadlocks every other thread; ``with lock:`` is the
       fix.
VC203  a ``guarded-by``/``requires-lock`` annotation naming a lock the
       class (or module) never defines — almost always a typo, and a
       typo here silently un-guards the field.

VC201–VC203 are intra-function and syntactic.  Two rules are
*interprocedural* over the package-wide call graph (the same
resolution every other family uses — analysis/callgraph.py: nested
defs, bare-name and from-imported calls, module-attribute chains,
``self.<method>`` through inheritance and subclass overrides):

VC204  a lock-order cycle: lock B acquired (directly or through a
       called function) while A is held on one path, and A while B is
       held on another.  Two threads entering the two paths deadlock;
       the fix is one documented order (or one lock).  Self-edges are
       skipped — re-entrant acquisition of the SAME lock is the RLock
       pattern the deploy control plane uses on purpose.
VC205  a blocking call while holding an *annotated* lock (one named in
       a ``guarded-by``/``requires-lock`` comment — the documented-
       discipline data locks): ``time.sleep``, network IO
       (urllib/requests/socket/http), file ``open``, ``.join()``/
       ``.wait()``/``queue.get()`` with no timeout,
       ``.block_until_ready()``, ``jax.device_get``.  Every other
       thread touching that lock's state stalls behind the block.
       Dedicated IO-serialization mutexes (held across IO by design)
       stay unannotated, so the rule binds exactly the locks whose
       contract is "short critical sections".

Lock identity canonicalizes to the attribute's defining class (an
``ArtifactRunner`` method holding ``self._page_lock`` shares the
``DecodeEngine`` node); lock flow through stored object references
remains out of scope — suppressions document the places that matters.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .findings import Finding
from .pysrc import ParsedFile, dotted_name


def _lock_key(text: str) -> str:
    """Normalize a lock spelling: ``self._lock`` and ``_lock`` both key
    on the attribute/name so annotation and ``with`` can't disagree on
    the ``self.`` prefix."""
    return text.split(".")[-1]


class _ClassGuards:
    def __init__(self, name: str):
        self.name = name
        self.fields: Dict[str, Tuple[str, int]] = {}  # field -> (lock, line)
        self.self_attrs: Set[str] = set()             # every self.X assigned
        #: methods annotated ``# requires-lock:`` -> the lock they need
        #: (their CALL SITES must hold it — annotating a method shifts
        #: the obligation to callers, it must not erase it)
        self.requires: Dict[str, str] = {}


def _collect_guards(pf: ParsedFile):
    """(class guards by class name, module-global guards name->(lock,
    line))."""
    classes: Dict[str, _ClassGuards] = {}
    module_guards: Dict[str, Tuple[str, int]] = {}

    for node in pf.tree.body:
        if isinstance(node, (ast.Assign, ast.AnnAssign)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            lock = pf.comments.guarded_by.get(node.lineno)
            if lock:
                for t in targets:
                    if isinstance(t, ast.Name):
                        module_guards[t.id] = (lock, node.lineno)

    for cls in ast.walk(pf.tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        cg = classes.setdefault(cls.name, _ClassGuards(cls.name))
        for node in ast.walk(cls):
            if isinstance(node, (ast.Assign, ast.AnnAssign)):
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                lock = pf.comments.guarded_by.get(node.lineno)
                for t in targets:
                    if isinstance(t, ast.Attribute) \
                            and isinstance(t.value, ast.Name) \
                            and t.value.id == "self":
                        cg.self_attrs.add(t.attr)
                        if lock and t.attr not in cg.fields:
                            cg.fields[t.attr] = (lock, node.lineno)
            elif isinstance(node, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                req = pf.comments.requires_lock.get(node.lineno)
                if req:
                    cg.requires[node.name] = req
    return classes, module_guards


class _MethodWalk:
    """Walk one function tracking held locks (``with`` nesting +
    ``requires-lock``) and enclosing try/finally releases."""

    def __init__(self, pf: ParsedFile, qualname: str, fn: ast.AST,
                 cls: Optional[_ClassGuards],
                 module_guards: Dict[str, Tuple[str, int]],
                 module_names: Set[str],
                 module_requires: Dict[str, str],
                 out: List[Finding]):
        self.pf = pf
        self.qualname = qualname
        self.fn = fn
        self.cls = cls
        self.module_guards = module_guards
        self.module_names = module_names
        self.module_requires = module_requires
        self.out = out
        self.held: Set[str] = set()
        self.finally_released: Set[str] = set()
        self.is_init = (fn.name in ("__init__", "__new__")
                        if hasattr(fn, "name") else False) \
            or fn.lineno in pf.comments.not_shared
        req = pf.comments.requires_lock.get(fn.lineno)
        if req:
            self._check_lock_exists(req, fn.lineno)
            self.held.add(_lock_key(req))

    def _emit(self, rule, line, col, message, hint):
        self.out.append(Finding(
            rule=rule, path=self.pf.relpath, line=line, col=col,
            message=message, hint=hint, symbol=self.qualname,
            snippet=self.pf.line_text(line)))

    def _check_lock_exists(self, lock: str, line: int):
        key = _lock_key(lock)
        known = key in self.module_names \
            or (self.cls is not None and key in self.cls.self_attrs)
        if not known:
            self._emit(
                "VC203", line, 0,
                f"annotation names lock `{lock}`, which is defined "
                "neither on the class nor at module level",
                "fix the lock name — a typo here silently un-guards "
                "the field")

    # -- traversal ----------------------------------------------------------
    def run(self):
        self._stmts(self.fn.body)

    def _stmts(self, body):
        for i, stmt in enumerate(body):
            nxt = body[i + 1] if i + 1 < len(body) else None
            self._stmt(stmt, nxt)

    def _stmt(self, stmt: ast.stmt, nxt: Optional[ast.stmt]):
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return                      # nested defs walked separately
        if isinstance(stmt, ast.With):
            prev = set(self.held)
            for item in stmt.items:
                text = dotted_name(item.context_expr)
                if text:
                    self.held.add(_lock_key(text))
                self._scan_expr(item.context_expr)
            self._stmts(stmt.body)
            self.held = prev
            return
        if isinstance(stmt, ast.Try):
            released = set()
            for f in ast.walk(ast.Module(body=stmt.finalbody,
                                         type_ignores=[])):
                if isinstance(f, ast.Call) \
                        and isinstance(f.func, ast.Attribute) \
                        and f.func.attr == "release":
                    text = dotted_name(f.func.value)
                    if text:
                        released.add(_lock_key(text))
            self.finally_released |= released
            self._stmts(stmt.body)
            self.finally_released -= released
            for h in stmt.handlers:
                self._stmts(h.body)
            self._stmts(stmt.orelse)
            self._stmts(stmt.finalbody)
            return
        # acquire() discipline (VC202): look at expression statements
        if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Call):
            call = stmt.value
            if isinstance(call.func, ast.Attribute) \
                    and call.func.attr == "acquire":
                base = dotted_name(call.func.value)
                key = _lock_key(base) if base else None
                ok = key is not None and (
                    key in self.finally_released
                    or self._next_releases(nxt, key))
                if not ok:
                    self._emit(
                        "VC202", stmt.lineno, stmt.col_offset,
                        f"bare `{base or '<lock>'}.acquire()` without a "
                        "try/finally release — an exception here "
                        "deadlocks every waiter",
                        f"use `with {base or '<lock>'}:` (or wrap the "
                        "critical section in try/finally)")
        # compound statements: keep sibling info so acquire-then-try
        # works anywhere, not just at function top level
        if isinstance(stmt, (ast.If, ast.While)):
            self._scan_expr(stmt.test)
            self._stmts(stmt.body)
            self._stmts(stmt.orelse)
            return
        if isinstance(stmt, ast.For):
            self._scan_expr(stmt.iter)
            self._scan_expr(stmt.target)
            self._stmts(stmt.body)
            self._stmts(stmt.orelse)
            return
        # generic statement: scan its expressions
        for child in ast.iter_child_nodes(stmt):
            if isinstance(child, ast.expr):
                self._scan_expr(child)
            elif isinstance(child, ast.stmt):
                self._stmt(child, None)

    @staticmethod
    def _next_releases(nxt: Optional[ast.stmt], key: str) -> bool:
        """``lock.acquire()`` directly followed by ``try: ...
        finally: lock.release()``."""
        if not isinstance(nxt, ast.Try):
            return False
        for f in ast.walk(ast.Module(body=nxt.finalbody,
                                     type_ignores=[])):
            if isinstance(f, ast.Call) \
                    and isinstance(f.func, ast.Attribute) \
                    and f.func.attr == "release":
                text = dotted_name(f.func.value)
                if text and _lock_key(text) == key:
                    return True
        return False

    def _scan_expr(self, node: ast.AST):
        for sub in ast.walk(node):
            if isinstance(sub, (ast.Lambda,)):
                continue
            if isinstance(sub, ast.Call) \
                    and isinstance(sub.func, ast.Attribute) \
                    and isinstance(sub.func.value, ast.Name) \
                    and sub.func.value.id == "self" \
                    and self.cls is not None \
                    and sub.func.attr in self.cls.requires:
                # calling a requires-lock method without the lock:
                # the annotation shifts the obligation here, not away
                lock = self.cls.requires[sub.func.attr]
                if not self.is_init and _lock_key(lock) not in self.held:
                    self._emit(
                        "VC201", sub.lineno, sub.col_offset,
                        f"`self.{sub.func.attr}()` requires "
                        f"`{lock}` held (its `# requires-lock:` "
                        "contract) but the caller does not hold it",
                        f"wrap the call in `with {lock}:`")
            if isinstance(sub, ast.Call) \
                    and isinstance(sub.func, ast.Name) \
                    and sub.func.id in self.module_requires:
                lock = self.module_requires[sub.func.id]
                if not self.is_init and _lock_key(lock) not in self.held:
                    self._emit(
                        "VC201", sub.lineno, sub.col_offset,
                        f"`{sub.func.id}()` requires `{lock}` held "
                        "(its `# requires-lock:` contract) but the "
                        "caller does not hold it",
                        f"wrap the call in `with {lock}:`")
            if isinstance(sub, ast.Attribute) \
                    and isinstance(sub.value, ast.Name) \
                    and sub.value.id == "self" and self.cls is not None:
                self._check_access(sub.attr, sub.lineno, sub.col_offset,
                                   f"self.{sub.attr}",
                                   self.cls.fields)
            elif isinstance(sub, ast.Name) and sub.id in self.module_guards:
                self._check_access(sub.id, sub.lineno, sub.col_offset,
                                   sub.id,
                                   {k: v for k, v in
                                    self.module_guards.items()})

    def _check_access(self, field: str, line: int, col: int,
                      spelled: str, table: Dict[str, Tuple[str, int]]):
        entry = table.get(field)
        if entry is None:
            return
        lock, _decl_line = entry
        if self.is_init:
            return                      # construction precedes sharing
        if _lock_key(lock) in self.held:
            return
        self._emit(
            "VC201", line, col,
            f"`{spelled}` is guarded by `{lock}` but is touched "
            f"without holding it",
            f"wrap the access in `with {lock}:` — or, if the caller "
            f"holds it, annotate the method `# requires-lock: {lock}`")


# -- VC204/VC205: the interprocedural lock graph ----------------------------

def _short(lock: str) -> str:
    """Canonical lock id (``rel:Class:attr``) -> display name."""
    return lock.rsplit(":", 1)[-1]


def check_lock_graph_package(graph, files: List[ParsedFile]
                             ) -> List[Finding]:
    """VC204 (lock-order cycles) + VC205 (blocking under an annotated
    lock), interprocedural over the **package-wide** call graph: a lock
    held in ``runtime/engine.py`` across a call into ``deploy.py`` that
    blocks (or acquires the locks in the reverse order) is in scope —
    the module-local closure that shipped with PR 10 could not see
    either.  Lock identity canonicalizes through class inheritance
    (:meth:`~.callgraph.PackageGraph.canonical_lock`), so
    ``ArtifactRunner`` methods touching ``DecodeEngine`` locks share the
    graph node while unrelated same-named locks never merge.  Findings
    are only emitted into files under analysis; summaries of unparsed
    files still contribute edges and blocking facts."""
    (trans_acq, trans_blk, edges, annotated, facts,
     calls) = graph.lock_analysis()
    parsed = {pf.relpath: pf for pf in files}
    out: List[Finding] = []

    for (rel, q) in sorted(facts):
        pf = parsed.get(rel)
        if pf is None:
            continue
        f = facts[(rel, q)]
        seen_lines: Set[int] = set()
        under = [(graph.canonical_lock(rel, key), line, why)
                 for key, line, why in f["under"]]
        for held, raw, line, tgts in calls[(rel, q)]:
            blk = next((trans_blk[t] for t in tgts
                        if trans_blk.get(t) is not None), None)
            if blk is None:
                continue
            where = "" if blk[2] == rel \
                else f" in {blk[2]}"
            for lock in held:
                under.append(
                    (lock, line, f"{blk[1]} via `{raw}()`{where}"))
        for lock, line, why in under:
            if lock not in annotated or line in seen_lines:
                continue
            seen_lines.add(line)
            out.append(Finding(
                rule="VC205", path=rel, line=line, col=0,
                message=f"blocking call ({why}) while holding "
                        f"`{_short(lock)}` — every thread touching "
                        "that lock's state stalls behind it",
                hint="move the blocking work outside the critical "
                     "section (snapshot under the lock, block outside)",
                symbol=q, snippet=pf.line_text(line)))

    # VC204: cycle detection over the canonical edge set (DFS; report
    # each cycle once, at its first edge site inside an analyzed file)
    adj: Dict[str, Set[str]] = {}
    for (a, b) in edges:
        adj.setdefault(a, set()).add(b)
    reported: Set[frozenset] = set()
    for start in sorted(adj):
        stack = [(start, [start])]
        while stack:
            cur, path = stack.pop()
            for nxt in sorted(adj.get(cur, ())):
                if nxt == start:
                    cyc = frozenset(path)
                    if cyc in reported:
                        continue
                    reported.add(cyc)
                    sites = sorted(
                        edges[(x, y)] + (x, y)
                        for x, y in zip(path, path[1:] + [start]))
                    sites = [s for s in sites if s[1] in parsed]
                    if not sites:
                        continue    # cycle fully outside this scan
                    line, rel, q, a, b = sites[0]
                    order = " -> ".join(_short(x)
                                        for x in path + [start])
                    out.append(Finding(
                        rule="VC204", path=rel, line=line, col=0,
                        message=f"lock-order cycle {order}: "
                                f"`{_short(b)}` is acquired while "
                                f"`{_short(a)}` is held here, and the "
                                "reverse order exists on another path "
                                "— two threads deadlock",
                        hint="pick ONE acquisition order (document it "
                             "on the lock definitions) or merge the "
                             "locks",
                        symbol=q, snippet=parsed[rel].line_text(line)))
                elif nxt not in path:
                    stack.append((nxt, path + [nxt]))
    out.sort(key=lambda fi: (fi.path, fi.line, fi.rule))
    return out


def check(pf: ParsedFile) -> List[Finding]:
    out: List[Finding] = []
    annotated = bool(pf.comments.guarded_by) \
        or bool(pf.comments.requires_lock)
    if annotated:
        classes, module_guards = _collect_guards(pf)
    else:   # no annotations: skip the per-class tree walks entirely
        classes, module_guards = {}, {}
    module_names = {n.id for s in pf.tree.body
                    if isinstance(s, (ast.Assign, ast.AnnAssign))
                    for n in (s.targets if isinstance(s, ast.Assign)
                              else [s.target])
                    if isinstance(n, ast.Name)}
    module_requires = {
        info.node.name: pf.comments.requires_lock[info.node.lineno]
        for q, info in pf.functions.items()
        if "." not in q and info.node.lineno in pf.comments.requires_lock
    } if annotated else {}
    # validate guarded-by lock names once, at the annotation site
    for cg in classes.values():
        for field, (lock, line) in cg.fields.items():
            key = _lock_key(lock)
            if key not in cg.self_attrs and key not in module_names:
                out.append(Finding(
                    rule="VC203", path=pf.relpath, line=line, col=0,
                    message=f"`{field}` is annotated guarded-by "
                            f"`{lock}`, which is defined neither on "
                            "the class nor at module level",
                    hint="fix the lock name — a typo here silently "
                         "un-guards the field",
                    symbol=cg.name, snippet=pf.line_text(line)))
    for name, (lock, line) in module_guards.items():
        if _lock_key(lock) not in module_names:
            out.append(Finding(
                rule="VC203", path=pf.relpath, line=line, col=0,
                message=f"`{name}` is annotated guarded-by `{lock}`, "
                        "which is not defined at module level",
                hint="fix the lock name — a typo here silently "
                     "un-guards the field",
                snippet=pf.line_text(line)))

    # the walk below runs in unannotated files too: VC202 (acquire
    # discipline) needs no guarded-by annotations to fire
    for q, info in pf.functions.items():
        cg = classes.get(info.cls) if info.cls else None
        _MethodWalk(pf, q, info.node, cg, module_guards,
                    module_names, module_requires, out).run()
    return out
