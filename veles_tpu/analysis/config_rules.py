"""VK3xx — config-key drift between code, declared defaults, and docs.

The config tree auto-vivifies (``root.common.anything`` silently
creates a node — veles_tpu/config.py), which is ergonomic and
treacherous: a typo'd read returns an empty node instead of failing,
and a deleted feature leaves its knob declared forever.  This rule
cross-references three sources of truth:

* **reads** — every statically visible ``root.common.*`` access in the
  package: attribute chains, ``.get("key", default)`` /
  ``.value("key", default)`` calls, ``getattr(root.common, "key",
  default)``, and single-assignment aliases
  (``serve = root.common.serve`` … ``serve.get("slots")``);
* **declarations** — ``root.common.<dotted> = default`` assignments in
  ``config.py`` (the ``_defaults()`` block);
* **docs** — literal ``root.common.<key>`` mentions anywhere under the
  docs directory (docs/configuration.md is the reference table).

VK301  a key read somewhere but declared nowhere (typo, or a knob that
       needs a default) — error.  Keys under
       ``registry.DYNAMIC_CONFIG_PREFIXES`` (the fault-injection
       switchboard) are exempt by design.
VK302  a declared key no code reads — dead weight; delete it or wire
       it up — warning.
VK303  a declared key the docs never mention — warning.
"""

from __future__ import annotations

import ast
import dataclasses
import os
from typing import Dict, List, Optional, Tuple

from .findings import Finding
from .pysrc import ParsedFile, dotted_name
from .registry import DYNAMIC_CONFIG_PREFIXES

_ROOT_PREFIX = "root.common"


@dataclasses.dataclass
class _Use:
    key: str
    path: str
    line: int
    col: int
    symbol: str
    snippet: str


def _literal_str(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _imports_config_root(pf: ParsedFile) -> bool:
    """Only treat ``root`` as the config tree in files that import it
    from a ``config`` module (or in config.py itself)."""
    target = pf.aliases.get("root", "")
    return target.endswith("config.root") \
        or os.path.basename(pf.relpath) == "config.py"


def _chain_key(pf: ParsedFile, node: ast.AST,
               aliases: Dict[str, str]) -> Optional[str]:
    """Dotted key relative to ``root.common`` for a chain expression,
    via the file's config aliases; None when the chain is unrelated."""
    dotted = dotted_name(node)
    if dotted is None:
        return None
    if dotted == _ROOT_PREFIX:
        return ""
    if dotted.startswith(_ROOT_PREFIX + "."):
        return dotted[len(_ROOT_PREFIX) + 1:]
    head, _, rest = dotted.partition(".")
    if head in aliases:
        prefix = aliases[head]
        if not rest:
            return prefix
        return f"{prefix}.{rest}" if prefix else rest
    return None


def _collect_declared(pf: ParsedFile) -> Dict[str, Tuple[int, str]]:
    """key -> (line, snippet) for every ``root.common.<key> = ...``."""
    out: Dict[str, Tuple[int, str]] = {}
    for node in ast.walk(pf.tree):
        if not isinstance(node, ast.Assign):
            continue
        for t in node.targets:
            dotted = dotted_name(t)
            if dotted and dotted.startswith(_ROOT_PREFIX + "."):
                key = dotted[len(_ROOT_PREFIX) + 1:]
                out.setdefault(key, (node.lineno,
                                     pf.line_text(node.lineno)))
    return out


def _symbol_at(pf: ParsedFile, line: int) -> str:
    best, best_span = "", None
    for q, info in pf.functions.items():
        node = info.node
        end = getattr(node, "end_lineno", node.lineno)
        if node.lineno <= line <= end:
            span = end - node.lineno
            if best_span is None or span < best_span:
                best, best_span = q, span
    return best


def _collect_uses(pf: ParsedFile) -> List[_Use]:
    if not _imports_config_root(pf):
        return []
    # pass 1: aliases of pure root.common chains (serve = root.common
    # .serve).  File-wide by name, BUT a name that is ever assigned
    # anything else anywhere in the file is disqualified — an unrelated
    # local `serve = {...}` in another function must not make its
    # `.get()` calls look like config reads (false VK301s).
    aliases: Dict[str, str] = {}
    poisoned = set()
    for node in ast.walk(pf.tree):
        if isinstance(node, (ast.Assign, ast.AnnAssign)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            if len(targets) != 1 or not isinstance(targets[0], ast.Name):
                continue
            name = targets[0].id
            dotted = dotted_name(node.value) if node.value else None
            if dotted == _ROOT_PREFIX:
                aliases[name] = ""
            elif dotted and dotted.startswith(_ROOT_PREFIX + "."):
                aliases[name] = dotted[len(_ROOT_PREFIX) + 1:]
            else:
                poisoned.add(name)
        # any other binding form sharing the name — a function
        # parameter, for/with/except/comprehension target — also
        # disqualifies it: `def f(serve): serve.get(...)` is not a
        # config read
        elif isinstance(node, ast.arg):
            poisoned.add(node.arg)
        elif isinstance(node, (ast.For, ast.comprehension)):
            for sub in ast.walk(node.target):
                if isinstance(sub, ast.Name):
                    poisoned.add(sub.id)
        elif isinstance(node, ast.withitem) \
                and node.optional_vars is not None:
            for sub in ast.walk(node.optional_vars):
                if isinstance(sub, ast.Name):
                    poisoned.add(sub.id)
        elif isinstance(node, ast.ExceptHandler) and node.name:
            poisoned.add(node.name)
    for name in poisoned:
        aliases.pop(name, None)

    uses: List[_Use] = []
    claimed = set()     # (line, col) of chain nodes consumed by a call

    def add(key: Optional[str], node: ast.AST):
        if key:         # "" = the root.common node itself: not a key
            uses.append(_Use(key, pf.relpath, node.lineno,
                             node.col_offset, _symbol_at(pf, node.lineno),
                             pf.line_text(node.lineno)))

    for node in ast.walk(pf.tree):
        if not isinstance(node, ast.Call):
            continue
        # root.common[.x].get("k", d) / .value("k", d)
        if isinstance(node.func, ast.Attribute) \
                and node.func.attr in ("get", "value") and node.args:
            prefix = _chain_key(pf, node.func.value, aliases)
            lit = _literal_str(node.args[0])
            if prefix is not None and lit is not None:
                key = f"{prefix}.{lit}" if prefix else lit
                add(key, node)
                _mark_claimed(node.func, claimed)
        # getattr(root.common[.x], "k", d)
        elif isinstance(node.func, ast.Name) and node.func.id == "getattr" \
                and len(node.args) >= 2:
            prefix = _chain_key(pf, node.args[0], aliases)
            lit = _literal_str(node.args[1])
            if prefix is not None and lit is not None:
                key = f"{prefix}.{lit}" if prefix else lit
                add(key, node)
                _mark_claimed(node.args[0], claimed)

    # bare chains (reads and writes), maximal only, not call-consumed
    parents: Dict[int, ast.AST] = {}
    for parent in ast.walk(pf.tree):
        for child in ast.iter_child_nodes(parent):
            parents[id(child)] = parent
    for node in ast.walk(pf.tree):
        if not isinstance(node, ast.Attribute):
            continue
        par = parents.get(id(node))
        if isinstance(par, ast.Attribute) and par.value is node:
            continue                     # not maximal
        if (node.lineno, node.col_offset) in claimed:
            continue
        if isinstance(par, ast.Call) and par.func is node:
            # ``root.common.mesh.items()``: the final attr is a Config
            # method, not a key segment — the key is the receiver chain
            node = node.value
            if not isinstance(node, (ast.Attribute, ast.Name)):
                continue
        key = _chain_key(pf, node, aliases)
        if key is None:
            continue
        # alias definitions themselves (serve = root.common.serve) are
        # node references, not leaf reads — recorded but harmless:
        # prefixes are always declared when any child is.
        add(key, node)
    return uses


def _mark_claimed(node: ast.AST, claimed: set):
    for sub in ast.walk(node):
        if isinstance(sub, ast.Attribute):
            claimed.add((sub.lineno, sub.col_offset))


def _docs_mentions(docs_dir: str) -> str:
    chunks = []
    for base, _dirs, files in os.walk(docs_dir):
        for fn in files:
            if fn.endswith((".md", ".rst", ".txt")):
                try:
                    with open(os.path.join(base, fn),
                              encoding="utf-8") as f:
                        chunks.append(f.read())
                except OSError:
                    continue
    return "\n".join(chunks)


def check(files: List[ParsedFile],
          docs_dir: Optional[str] = None, *,
          package_scan: Optional[bool] = None) -> List[Finding]:
    config_files = [pf for pf in files
                    if os.path.basename(pf.relpath) == "config.py"]
    declared: Dict[str, Tuple[str, int, str]] = {}
    for pf in config_files:
        for key, (line, snippet) in _collect_declared(pf).items():
            declared.setdefault(key, (pf.relpath, line, snippet))
    if not declared:
        return []                        # nothing to drift against
    prefixes = set()
    for key in declared:
        parts = key.split(".")
        for i in range(1, len(parts)):
            prefixes.add(".".join(parts[:i]))

    uses: List[_Use] = []
    for pf in files:
        if pf in config_files:
            continue
        uses.extend(_collect_uses(pf))

    out: List[Finding] = []
    used_keys = set()
    for u in uses:
        used_keys.add(u.key)
        if u.key in declared or u.key in prefixes:
            continue
        if any(u.key == p or u.key.startswith(p + ".")
               for p in DYNAMIC_CONFIG_PREFIXES):
            continue
        out.append(Finding(
            rule="VK301", path=u.path, line=u.line, col=u.col,
            message=f"config key `root.common.{u.key}` is read here "
                    "but declared nowhere in config.py (auto-"
                    "vivification would hand back an empty node)",
            hint="declare a default in config.py _defaults() — or fix "
                 "the key name",
            symbol=u.symbol, snippet=u.snippet))

    # VK302/VK303 claim a key is read/documented NOWHERE — only
    # provable against the whole package; a subset scan (--changed
    # touching config.py alone) must not declare every key dead
    if package_scan is False:
        return out
    docs_text = ""
    if docs_dir and os.path.isdir(docs_dir):
        docs_text = _docs_mentions(docs_dir)
    for key, (path, line, snippet) in sorted(declared.items()):
        leaf_used = key in used_keys or any(
            k.startswith(key + ".") for k in used_keys)
        if not leaf_used:
            out.append(Finding(
                rule="VK302", path=path, line=line, col=0,
                message=f"config key `root.common.{key}` is declared "
                        "but no code reads it",
                hint="delete the declaration or wire the knob up",
                symbol="_defaults", snippet=snippet))
        if docs_text and f"root.common.{key}" not in docs_text:
            out.append(Finding(
                rule="VK303", path=path, line=line, col=0,
                message=f"config key `root.common.{key}` is not "
                        "documented anywhere under docs/",
                hint="add it to docs/configuration.md",
                symbol="_defaults", snippet=snippet))
    return out
