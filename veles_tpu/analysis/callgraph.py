"""Whole-package call-graph resolution — the cross-module closure layer.

Every rule family used to close its reachability scope *module-locally*
(``pysrc.local_closure``): a deliberate blind spot while the contracts
being checked (trace scope, lock order, builder routing, shard_map
scope, resource lifecycles) stayed inside one file.  They no longer do —
``runtime/engine.py`` compiles builders defined in
``runtime/generate.py``, REST handlers reach through ``deploy.py`` into
the engine's locks, and ``ArtifactRunner`` overrides ``DecodeEngine``
hooks from another module.  This module closes the gap, still pure-AST
and jax-free:

* :func:`summarize` distills one :class:`~.pysrc.ParsedFile` into a
  JSON-serializable **summary**: exported defs, class bases and
  ``self.*`` attrs, import aliases, candidate outgoing references per
  function, lock-acquisition facts, thread-lifecycle facts, and the
  def-line markers (``# trace-root:`` etc).  Summaries are everything
  the cross-module closures need — no AST required — so they cache.

* :class:`PackageGraph` resolves references package-wide:
  ``from x import y`` names, module-attribute calls
  (``generate.make_decode_fn``), ``ClassName.method`` chains, and
  ``self.m()`` through class inheritance **including subclass
  overrides** (a ``DecodeEngine`` host loop calling ``self._prefill_fn``
  reaches ``ArtifactRunner._prefill_fn`` too).  On top of resolution it
  computes the package closures every family consumes: traced scope
  (VT1xx), shard-map scope (VS5xx), host-loop reach (VP603), the
  transitive lock/blocking summaries (VC204/VC205) and resource
  release reach (VR701).  ``cross_module=False`` restricts resolution
  to each file — the legacy scope, kept so tests can prove the blind
  spot is closed (and ``--local`` can bisect a finding).

* the **summary cache** (``.veles-lint-cache.json``, gitignored): per
  file, keyed by content hash, plus a whole-run findings memo keyed by
  the package-wide context digest.  ``--changed`` parses only the
  changed files and feeds the closure from cached summaries; a warm
  full-package run skips straight to the memoized findings.  Any edit
  invalidates exactly that file's summary (content hash) and the
  findings memo (context digest) — never another file's summary.

Lock identity is *canonicalized*: ``self._page_lock`` acquired in an
``ArtifactRunner`` method keys to the class that defines the attribute
(``DecodeEngine``), so cross-module aliasing through inheritance does
not split the lock graph, while same-named locks of unrelated classes
never merge (the module-local analyzer keyed on the bare attribute
name, which would create false cycles package-wide).
"""

from __future__ import annotations

import ast
import hashlib
import json
import os
from typing import Dict, Iterable, List, Optional, Set, Tuple

from .pysrc import ParsedFile, dotted_name
from .registry import (BUILDER, HOST_LOOP_ROOTS, SHARD_MAP_ROOTS,
                       TRACE_ROOTS, TRACED)

#: bumped whenever the summary format changes shape (cache entries from
#: an older analyzer are discarded wholesale via the analyzer digest,
#: but the explicit version keeps hand-inspection honest).
SUMMARY_VERSION = 1

CACHE_NAME = ".veles-lint-cache.json"


def module_name(relpath: str) -> str:
    """``veles_tpu/runtime/engine.py`` -> ``veles_tpu.runtime.engine``;
    ``pkg/__init__.py`` -> ``pkg``."""
    mod = relpath[:-3] if relpath.endswith(".py") else relpath
    parts = mod.replace(os.sep, "/").split("/")
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts)


def registry_entry(table: dict, relpath: str):
    """Longest path-suffix registry key matching ``relpath`` — the
    lookup convention every registry table shares."""
    best, entry = "", None
    for key, val in table.items():
        if (relpath == key or relpath.endswith("/" + key)) \
                and len(key) > len(best):
            best, entry = key, val
    return entry


# -- blocking-call inventory (shared with concurrency_rules) -----------------

#: modules whose any call blocks (network / subprocess IO).
BLOCKING_MODULES = ("urllib", "requests", "socket", "subprocess", "http")

#: method names that block when called with no timeout argument.
TIMEOUT_METHODS = ("join", "wait", "get")


def blocking_reason(pf: ParsedFile, node: ast.Call) -> Optional[str]:
    """A short description when the call blocks, else None."""
    chain = dotted_name(node.func)
    resolved = pf.resolve_chain(chain) if chain else None
    if resolved is not None:
        head = resolved.split(".")[0]
        if resolved == "time.sleep":
            return "time.sleep"
        if head in BLOCKING_MODULES and "." in resolved:
            return f"`{chain}` (network/process IO)"
        if resolved == "jax.device_get":
            return "jax.device_get (device sync)"
    if isinstance(node.func, ast.Name) and node.func.id == "open":
        return "open() (file IO)"
    if isinstance(node.func, ast.Attribute):
        attr = node.func.attr
        if attr == "block_until_ready":
            return ".block_until_ready() (device sync)"
        if attr in TIMEOUT_METHODS and not node.args:
            t = next((k.value for k in node.keywords
                      if k.arg == "timeout"), None)
            if t is None or (isinstance(t, ast.Constant)
                             and t.value is None):
                return f".{attr}() with no timeout"
    return None


# -- summaries ---------------------------------------------------------------

def _raw_lock(chain: str, cls: Optional[str]) -> Tuple[str, str]:
    """(scope, attr) for a lock spelling: ``self._x`` scopes to the
    class, a bare name to the module (``""``), a two-part
    ``mod._lock`` chain to its head (``"@mod"`` — resolved through the
    import aliases at canonicalization), anything longer to the
    file-local ``"?"`` scope (never merged across files)."""
    parts = chain.split(".")
    if parts[0] == "self" and len(parts) == 2 and cls:
        return (cls, parts[1])
    if len(parts) == 1:
        return ("", parts[0])
    if len(parts) == 2 and parts[0] != "self":
        return ("@" + parts[0], parts[1])
    return ("?", parts[-1])


def _collect_refs(pf: ParsedFile, info, known: Set[str]) -> List[list]:
    """Candidate outgoing references of one function body (nested
    ``def``s excluded — they have their own summaries and the closure
    expands children): bare ``Name`` loads and dotted chains whose head
    could resolve (a module def/class, an import alias, or ``self``).
    Deduplicated on the raw spelling."""
    out: List[list] = []
    seen: Set[str] = set()

    def add(raw: str, line: int):
        if raw not in seen:
            seen.add(raw)
            out.append([raw, line])

    skip_spans: List[Tuple[int, int]] = []
    for child in ast.walk(info.node):
        if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and child is not info.node:
            skip_spans.append(
                (child.lineno, getattr(child, "end_lineno", child.lineno)))

    def skipped(line: int) -> bool:
        return any(lo <= line <= hi for lo, hi in skip_spans)

    for node in ast.walk(info.node):
        line = getattr(node, "lineno", 0)
        if line and skipped(line):
            continue
        if isinstance(node, ast.Name):
            if node.id in known or node.id in pf.aliases:
                add(node.id, line)
        elif isinstance(node, ast.Attribute):
            chain = dotted_name(node)
            if chain is None:
                continue
            parts = chain.split(".")
            if len(parts) > 4:
                continue
            head = parts[0]
            if head == "self" and len(parts) == 2 and info.cls:
                add(chain, line)
            elif head in pf.aliases or head in known:
                add(chain, line)
    return out


def _lock_facts(pf: ParsedFile, q, info) -> dict:
    """Per-function direct lock facts with (scope, attr) raw keys:
    acquisitions, nesting edges, direct blocking calls (with and
    without locks held), and call sites annotated with the held-lock
    set — the inputs of the package-level VC204/VC205 pass."""
    facts = {"acq": {}, "edges": [], "blk": None, "under": [],
             "calls": []}
    entry_held: List[Tuple[str, str]] = []
    req = pf.comments.requires_lock.get(info.node.lineno)
    if req:
        entry_held.append(_raw_lock(req, info.cls))

    def key(raw: Tuple[str, str]) -> str:
        return f"{raw[0]}|{raw[1]}"

    def walk(stmts, held):
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue
            if isinstance(stmt, ast.With):
                inner = list(held)
                for item in stmt.items:
                    text = dotted_name(item.context_expr)
                    if text:
                        raw = _raw_lock(text, info.cls)
                        facts["acq"].setdefault(key(raw), stmt.lineno)
                        for h in inner:
                            if h != raw:
                                facts["edges"].append(
                                    [key(h), key(raw), stmt.lineno])
                        if raw not in inner:
                            inner.append(raw)
                    else:
                        scan_expr(item.context_expr, held)
                walk(stmt.body, inner)
                continue
            for child in ast.iter_child_nodes(stmt):
                if isinstance(child, ast.expr):
                    scan_expr(child, held)
                elif isinstance(child, ast.stmt):
                    walk([child], held)
                elif isinstance(child, ast.ExceptHandler):
                    walk(child.body, held)

    def scan_expr(node, held):
        for sub in ast.walk(node):
            if not isinstance(sub, ast.Call):
                continue
            why = blocking_reason(pf, sub)
            if why is not None:
                if facts["blk"] is None:
                    facts["blk"] = [sub.lineno, why]
                for h in held:
                    facts["under"].append([key(h), sub.lineno, why])
            chain = dotted_name(sub.func)
            if chain is not None:
                facts["calls"].append(
                    [[key(h) for h in held], chain, sub.lineno])

    walk(info.node.body, list(entry_held))
    # dedup edges/calls on their identifying tuple, keep first lines
    facts["edges"] = [list(t) for t in dict.fromkeys(
        tuple(e) for e in facts["edges"])]
    facts["calls"] = [[list(h), r, ln] for h, r, ln in dict.fromkeys(
        (tuple(h), r, ln) for h, r, ln in facts["calls"])]
    return facts


def _thread_facts(pf: ParsedFile) -> dict:
    """VR702 inputs: every ``threading.Thread(...)`` construction (with
    its daemon kwarg, binding target and enclosing symbol), plus the
    attribute/local names the file ``.join()``s or sets ``.daemon`` on."""
    threads: List[dict] = []
    joins: Set[str] = set()
    daemon_sets: Set[str] = set()

    for node in ast.walk(pf.tree):
        if isinstance(node, ast.Call) and isinstance(node.func,
                                                     ast.Attribute) \
                and node.func.attr == "join":
            base = dotted_name(node.func.value)
            if base:
                joins.add(base.split(".")[-1])
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            t = node.targets[0]
            if isinstance(t, ast.Attribute) and t.attr == "daemon" \
                    and isinstance(node.value, ast.Constant) \
                    and node.value.value is True:
                base = dotted_name(t.value)
                if base:
                    daemon_sets.add(base.split(".")[-1])

    def symbol_at(line: int) -> str:
        best, span = "", None
        for q, info in pf.functions.items():
            end = getattr(info.node, "end_lineno", info.node.lineno)
            if info.node.lineno <= line <= end:
                s = end - info.node.lineno
                if span is None or s < span:
                    best, span = q, s
        return best

    targets: Dict[int, str] = {}        # id(Thread call) -> bound name
    for node in ast.walk(pf.tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.value, ast.Call):
            t = node.targets[0]
            name = None
            if isinstance(t, ast.Attribute):
                name = t.attr
            elif isinstance(t, ast.Name):
                name = t.id
            if name:
                targets[id(node.value)] = name

    for node in ast.walk(pf.tree):
        if not isinstance(node, ast.Call):
            continue
        chain = dotted_name(node.func)
        if chain is None or pf.resolve_chain(chain) != "threading.Thread":
            continue
        daemon = None
        for kw in node.keywords:
            if kw.arg == "daemon" and isinstance(kw.value, ast.Constant):
                daemon = bool(kw.value.value)
        threads.append({"line": node.lineno, "daemon": daemon,
                        "target": targets.get(id(node)),
                        "symbol": symbol_at(node.lineno)})
    return {"threads": threads, "joins": sorted(joins),
            "daemon_sets": sorted(daemon_sets)}


def summarize(pf: ParsedFile) -> dict:
    """The serializable cross-module summary of one parsed file."""
    defs = {q: info.node.lineno for q, info in pf.functions.items()}
    cls_of = {q: (info.cls or "") for q, info in pf.functions.items()
              if info.cls}
    classes: Dict[str, List[str]] = {}
    attrs: Dict[str, List[str]] = {}
    for node in ast.walk(pf.tree):
        if not isinstance(node, ast.ClassDef):
            continue
        bases = []
        for b in node.bases:
            chain = dotted_name(b)
            if chain:
                bases.append(chain)
        classes[node.name] = bases
        own: Set[str] = set()
        for sub in ast.walk(node):
            if isinstance(sub, (ast.Assign, ast.AnnAssign)):
                targets = sub.targets if isinstance(sub, ast.Assign) \
                    else [sub.target]
                for t in targets:
                    if isinstance(t, ast.Attribute) \
                            and isinstance(t.value, ast.Name) \
                            and t.value.id == "self":
                        own.add(t.attr)
        attrs[node.name] = sorted(own)

    known = set(defs) | set(classes)
    refs = {}
    locks = {}
    fincalls = {}
    for q, info in pf.functions.items():
        r = _collect_refs(pf, info, known)
        if r:
            refs[q] = r
        locks[q] = _lock_facts(pf, q, info)
        # final names of every call in the body (receiver-agnostic):
        # how VR701 sees `pool.free(h)` — the receiver object is not
        # statically resolvable, the method name is
        names = sorted({n for n in (
            (node.func.id if isinstance(node.func, ast.Name)
             else node.func.attr if isinstance(node.func, ast.Attribute)
             else None)
            for node in ast.walk(info.node)
            if isinstance(node, ast.Call)) if n})
        if names:
            fincalls[q] = names

    # annotated locks, qualified by the class enclosing the comment line
    cls_spans = []
    for node in ast.walk(pf.tree):
        if isinstance(node, ast.ClassDef):
            cls_spans.append((node.lineno,
                              getattr(node, "end_lineno", node.lineno),
                              node.name))

    def cls_at(line: int) -> Optional[str]:
        best, span = None, None
        for lo, hi, name in cls_spans:
            if lo <= line <= hi and (span is None or hi - lo < span):
                best, span = name, hi - lo
        return best

    ann = set()
    for line, lock in list(pf.comments.guarded_by.items()) \
            + list(pf.comments.requires_lock.items()):
        scope, attr = _raw_lock(lock, cls_at(line))
        ann.add(f"{scope}|{attr}")

    markers = {"trace": {}, "shard": {}, "host": [],
               "acquire": {}, "release": {}, "durable": []}
    for q, info in pf.functions.items():
        ln = info.node.lineno
        mode = pf.comments.trace_root.get(ln)
        if mode:
            markers["trace"][q] = TRACED if mode == "traced" else BUILDER
        env = pf.comments.shard_map_root.get(ln)
        if env is not None:
            markers["shard"][q] = list(env)
        if ln in pf.comments.host_loop_root:
            markers["host"].append(q)
        res = pf.comments.resource_acquire.get(ln)
        if res:
            markers["acquire"][q] = res
        res = pf.comments.resource_release.get(ln)
        if res:
            markers["release"][q] = res
        if ln in pf.comments.durable_write:
            markers["durable"].append(q)

    return {"module": module_name(pf.relpath), "defs": defs,
            "cls_of": cls_of, "classes": classes, "attrs": attrs,
            "aliases": dict(pf.aliases), "refs": refs, "locks": locks,
            "fincalls": fincalls, "ann_locks": sorted(ann),
            "markers": markers, **_thread_facts(pf)}


# -- the graph ---------------------------------------------------------------

class PackageGraph:
    """Package-wide resolution and closures over per-file summaries.

    ``cross_module=False`` restricts every resolution to the reference's
    own file — the legacy module-local closure, byte-compatible with the
    pre-graph analyzer (used by tests to prove a cross-module seed is
    invisible to it, and by ``--local`` to bisect findings)."""

    def __init__(self, summaries: Dict[str, dict], *,
                 cross_module: bool = True):
        self.summaries = summaries
        self.cross_module = cross_module
        self.modules: Dict[str, str] = {
            s["module"]: rel for rel, s in summaries.items()}
        # class name -> [(relpath, base chains)] across the package
        self.classes: Dict[str, List[str]] = {}
        for rel, s in summaries.items():
            for cname in s["classes"]:
                self.classes.setdefault(cname, []).append(rel)
        self._subclasses: Optional[Dict[Tuple[str, str],
                                        List[Tuple[str, str]]]] = None
        self._resolve_memo: Dict[Tuple[str, Optional[str], str],
                                 Tuple[Tuple[str, str], ...]] = {}
        self._tscope_memo: Optional[Dict[Tuple[str, str], bool]] = None

    # -- module / class resolution ------------------------------------------
    def resolve_module(self, dotted: str, importer: str) -> Optional[str]:
        """Module dotted name -> relpath.  Relative names (leading dots)
        resolve against the importing module; absolute names match
        exactly, then by unique dotted suffix (fixture trees anchor
        display paths at a tmp dir the import never names)."""
        if dotted.startswith("."):
            level = len(dotted) - len(dotted.lstrip("."))
            rest = dotted.lstrip(".")
            base = self.summaries[importer]["module"].split(".")
            base = base[:len(base) - level] if level <= len(base) else []
            dotted = ".".join(base + ([rest] if rest else []))
        rel = self.modules.get(dotted)
        if rel is not None:
            return rel
        hits = [r for m, r in self.modules.items()
                if m.endswith("." + dotted)]
        return hits[0] if len(hits) == 1 else None

    def _class_home(self, rel: str, cname: str) -> Optional[str]:
        """The file defining class ``cname`` as seen from ``rel``:
        local definition first, then the import alias, then (cross
        module) a unique package-wide definition."""
        s = self.summaries.get(rel)
        if s is None:
            return None
        if cname in s["classes"]:
            return rel
        if not self.cross_module:
            return None
        canon = s["aliases"].get(cname)
        if canon:
            mod, _, leaf = canon.rpartition(".")
            if leaf == cname and mod:
                home = self.resolve_module(mod, rel)
                if home and cname in self.summaries[home]["classes"]:
                    return home
        homes = self.classes.get(cname, [])
        return homes[0] if len(homes) == 1 else None

    def _mro(self, rel: str, cname: str,
             limit: int = 10) -> List[Tuple[str, str]]:
        """Linearized (relpath, class) chain: the class then its bases,
        resolved through imports; unresolvable bases are dropped."""
        out: List[Tuple[str, str]] = []
        seen: Set[Tuple[str, str]] = set()
        work = [(rel, cname)]
        while work and len(out) < limit:
            r, c = work.pop(0)
            home = self._class_home(r, c)
            if home is None or (home, c) in seen:
                continue
            seen.add((home, c))
            out.append((home, c))
            for base in self.summaries[home]["classes"].get(c, ()):
                leaf = base.split(".")[-1]
                work.append((home, leaf))
        return out

    def subclasses(self, rel: str, cname: str) -> List[Tuple[str, str]]:
        """Known package subclasses of (rel, cname), transitively."""
        if self._subclasses is None:
            index: Dict[Tuple[str, str], List[Tuple[str, str]]] = {}
            for r, s in self.summaries.items():
                for c in s["classes"]:
                    for home, base in self._mro(r, c)[1:]:
                        index.setdefault((home, base), []).append((r, c))
            self._subclasses = index
        if not self.cross_module:
            return [(r, c) for r, c in
                    self._subclasses.get((rel, cname), []) if r == rel]
        return self._subclasses.get((rel, cname), [])

    def _method(self, rel: str, cname: str,
                meth: str) -> Optional[Tuple[str, str]]:
        """The defining (relpath, qualname) of ``cname.meth`` walking
        the MRO."""
        for r, c in self._mro(rel, cname):
            if f"{c}.{meth}" in self.summaries[r]["defs"]:
                return (r, f"{c}.{meth}")
        return None

    # -- reference resolution -----------------------------------------------
    def resolve(self, rel: str, cls: Optional[str],
                raw: str) -> List[Tuple[str, str]]:
        """All (relpath, qualname) targets a raw reference may reach.

        ``self.m`` resolves through the enclosing class's MRO *plus*
        every package subclass override (dynamic dispatch from a base
        method can land there); bare names resolve to module defs then
        through ``from x import y``; dotted chains resolve through
        module aliases (``generate.make_decode_fn``) and local/imported
        classes (``DecodePlan.step``)."""
        memo_key = (rel, cls, raw)
        hit = self._resolve_memo.get(memo_key)
        if hit is not None:
            return list(hit)
        out = self._resolve(rel, cls, raw)
        self._resolve_memo[memo_key] = tuple(out)
        return out

    def _resolve(self, rel, cls, raw):
        s = self.summaries.get(rel)
        if s is None:
            return []
        parts = raw.split(".")
        out: List[Tuple[str, str]] = []
        if parts[0] == "self" and len(parts) == 2 and cls:
            meth = parts[1]
            base = self._method(rel, cls, meth)
            if base is None:
                return []
            out.append(base)
            mro = self._mro(rel, cls)
            if mro:
                # dynamic dispatch: a base method calling self.m() can
                # land in any package subclass override
                for r2, c2 in self.subclasses(*mro[0]):
                    q2 = f"{c2}.{meth}"
                    if q2 in self.summaries[r2]["defs"] \
                            and (r2, q2) != base:
                        out.append((r2, q2))
            return out
        if len(parts) == 1:
            name = raw
            if name in s["defs"] and "." not in name:
                return [(rel, name)]
            if not self.cross_module:
                return []
            canon = s["aliases"].get(name)
            if canon and canon != name:
                return self._resolve_canonical(canon, rel)
            return []
        # dotted: ClassName.method on a local or imported class, or a
        # module-attribute chain through an import alias
        head = parts[0]
        if head in s["classes"] or (self.cross_module
                                    and self._class_home(rel, head)):
            home = self._class_home(rel, head)
            if home is not None and len(parts) == 2:
                m = self._method(home, head, parts[1])
                return [m] if m else []
            return []
        canon = s["aliases"].get(head)
        if canon is None:
            return []
        if not self.cross_module:
            return []
        return self._resolve_canonical(
            canon + "." + ".".join(parts[1:]), rel)

    def _resolve_canonical(self, canon: str, importer: str):
        """``veles_tpu.runtime.generate.make_decode_fn`` (or a relative
        ``.generate.make_decode_fn``) -> defining (relpath, qualname),
        trying the longest module prefix first so
        ``pkg.mod.Class.method`` splits correctly."""
        lead = ""
        while canon.startswith("."):
            lead += "."
            canon = canon[1:]
        parts = canon.split(".")
        for cut in range(len(parts) - 1, 0, -1):
            mod = lead + ".".join(parts[:cut])
            target = self.resolve_module(mod, importer)
            if target is None:
                continue
            qual = ".".join(parts[cut:])
            tdefs = self.summaries[target]["defs"]
            if qual in tdefs:
                return [(target, qual)]
            # imported class referenced bare: not a function target
            return []
        return []

    # -- closures ------------------------------------------------------------
    def closure(self, roots: Iterable[Tuple[str, str]]
                ) -> Set[Tuple[str, str]]:
        """Roots + nested ``def``s + transitively referenced functions,
        resolved package-wide (or module-locally when
        ``cross_module=False``)."""
        seen: Set[Tuple[str, str]] = set()
        work: List[Tuple[str, str]] = []
        for rel, q in roots:
            s = self.summaries.get(rel)
            if s is not None and q in s["defs"]:
                seen.add((rel, q))
                work.append((rel, q))
        while work:
            rel, q = work.pop()
            s = self.summaries[rel]
            for q2 in s["defs"]:
                if q2.startswith(q + ".") and (rel, q2) not in seen:
                    seen.add((rel, q2))
                    work.append((rel, q2))
            cls = s["cls_of"].get(q) or None
            for raw, _line in s["refs"].get(q, ()):
                for tgt in self.resolve(rel, cls, raw):
                    if tgt not in seen:
                        seen.add(tgt)
                        work.append(tgt)
        return seen

    def traced_scope(self, overrides: Optional[dict] = None
                     ) -> Dict[Tuple[str, str], bool]:
        """(relpath, qualname) -> params-tainted for every function in
        traced scope, package-wide: declared roots keep their declared
        mode, nested ``def``s are the literal jit/scan bodies (tainted),
        and functions a traced body merely references join with
        untainted parameters — the per-file semantics of the legacy
        closure, closed over the whole package.  Memoized for the
        no-overrides case: the default run computes this fixpoint once,
        shared by VT1xx and the VP6xx program scope."""
        if overrides is None and self._tscope_memo is not None:
            return self._tscope_memo
        table = overrides if overrides is not None else TRACE_ROOTS
        modes: Dict[Tuple[str, str], bool] = {}
        work: List[Tuple[str, str]] = []
        for rel, s in self.summaries.items():
            entry = registry_entry(table, rel) or {}
            roots = dict(entry)
            roots.update(s["markers"]["trace"])
            for q, mode in roots.items():
                if q in s["defs"]:
                    modes[(rel, q)] = mode == TRACED
                    work.append((rel, q))
        while work:
            rel, q = work.pop()
            s = self.summaries[rel]
            for q2 in s["defs"]:
                if q2.startswith(q + ".") \
                        and "." not in q2[len(q) + 1:] \
                        and (rel, q2) not in modes:
                    modes[(rel, q2)] = True
                    work.append((rel, q2))
            cls = s["cls_of"].get(q) or None
            for raw, _line in s["refs"].get(q, ()):
                for tgt in self.resolve(rel, cls, raw):
                    if tgt not in modes:
                        modes[tgt] = False
                        work.append(tgt)
        if overrides is None:
            self._tscope_memo = modes
        return modes

    def shard_scope(self) -> Dict[Tuple[str, str], Tuple[str, ...]]:
        """(relpath, qualname) -> bound-axes environment for every
        function inside a shard_map scope: the union of the axes of
        every root whose closure reaches it."""
        env: Dict[Tuple[str, str], Set[str]] = {}
        for rel, s in self.summaries.items():
            roots: Dict[str, Tuple[str, ...]] = {}
            entry = registry_entry(SHARD_MAP_ROOTS, rel)
            if entry:
                roots.update(entry)
            for q, axes in s["markers"]["shard"].items():
                roots[q] = tuple(axes)
            for q, axes in roots.items():
                for tgt in self.closure([(rel, q)]):
                    env.setdefault(tgt, set()).update(axes)
        return {k: tuple(sorted(v)) for k, v in env.items()}

    def host_scope(self) -> Set[Tuple[str, str]]:
        """Functions reachable from the registered host hot loops
        (scheduler ticks, REST handlers) package-wide — VP603's reach."""
        roots: List[Tuple[str, str]] = []
        for rel, s in self.summaries.items():
            entry = registry_entry(HOST_LOOP_ROOTS, rel) or ()
            for q in list(entry) + s["markers"]["host"]:
                roots.append((rel, q))
        return self.closure(roots)

    def program_scope(self) -> Set[Tuple[str, str]]:
        """Everything inside the traced-program closure (both root
        modes): builder calls here are build-time composition, exempt
        from the VP601/VP603 host-boundary rules."""
        return set(self.traced_scope())

    # -- lock graph -----------------------------------------------------------
    def canonical_lock(self, rel: str, key: str) -> str:
        """``scope|attr`` -> package-canonical lock id.  Class-scoped
        locks canonicalize to the class that *defines* the attribute
        (MRO walk), so ``self._page_lock`` held in an ``ArtifactRunner``
        method and in a ``DecodeEngine`` method are the same lock.
        Module-level locks canonicalize to their *defining module*
        through the import aliases, so ``from eng import _lock`` (or
        ``with eng._lock:``) merges with the ``guarded-by`` annotation
        in ``eng.py``."""
        scope, _, attr = key.partition("|")
        if scope.startswith("@"):
            # `mod._lock` chain: resolve the head as an imported module
            if self.cross_module:
                s = self.summaries.get(rel, {})
                canon = s.get("aliases", {}).get(scope[1:])
                if canon:
                    home = self.resolve_module(canon, rel)
                    if home is not None:
                        return f"{home}::{attr}"
            # unresolvable object head: keep the head in the id so
            # `a._lock` and `b._lock` (distinct objects) never merge
            # into one node — a merge would mint self-edge "deadlocks"
            return f"{rel}:?{scope[1:]}:{attr}"
        if scope and scope != "?":
            for r, c in self._mro(rel, scope):
                if attr in self.summaries[r]["attrs"].get(c, ()):
                    return f"{r}:{c}:{attr}"
            return f"{rel}:{scope}:{attr}"
        if scope == "?":
            return f"{rel}:?:{attr}"
        # bare module-level name: a from-import of another module's
        # global canonicalizes at the definition site
        if self.cross_module:
            s = self.summaries.get(rel, {})
            canon = s.get("aliases", {}).get(attr)
            if canon:
                mod, _, leaf = canon.rpartition(".")
                if leaf == attr and mod:
                    home = self.resolve_module(mod, rel)
                    if home is not None:
                        return f"{home}::{attr}"
        return f"{rel}::{attr}"

    def lock_analysis(self):
        """Package-wide transitive lock facts::

            (trans_acq, trans_blk, edges, annotated)

        * ``trans_acq[(rel, q)]`` — canonical locks acquired by the
          function or anything it (transitively) calls;
        * ``trans_blk[(rel, q)]`` — ``(line, why, rel)`` of the first
          blocking call reachable from the function, else None;
        * ``edges[(a, b)]`` — ``(line, rel, qual)`` witness where lock
          ``b`` is acquired (possibly through calls) while ``a`` held;
        * ``annotated`` — canonical ids of every ``guarded-by``/
          ``requires-lock``-annotated lock in the package.
        """
        facts: Dict[Tuple[str, str], dict] = {}
        canon_memo: Dict[Tuple[str, str], str] = {}

        def canon(rel, key):
            hit = canon_memo.get((rel, key))
            if hit is None:
                hit = self.canonical_lock(rel, key)
                canon_memo[(rel, key)] = hit
            return hit

        calls: Dict[Tuple[str, str], List] = {}
        for rel, s in self.summaries.items():
            for q, f in s["locks"].items():
                node = {"acq": {canon(rel, k): ln
                                for k, ln in f["acq"].items()},
                        # raw-distinct spellings can canonicalize to
                        # one lock (aliases, inheritance): a collapsed
                        # edge is re-entrancy, not an ordering cycle
                        "edges": [(ca, cb, ln)
                                  for a, b, ln in f["edges"]
                                  for ca, cb in [(canon(rel, a),
                                                  canon(rel, b))]
                                  if ca != cb],
                        "blk": f["blk"], "under": f["under"]}
                facts[(rel, q)] = node
                cls = s["cls_of"].get(q) or None
                resolved = []
                for held, raw, line in f["calls"]:
                    tgts = self.resolve(rel, cls, raw)
                    if tgts:
                        resolved.append(
                            ([canon(rel, h) for h in held], raw,
                             line, tgts))
                calls[(rel, q)] = resolved

        trans_acq = {k: set(v["acq"]) for k, v in facts.items()}
        trans_blk: Dict[Tuple[str, str], Optional[tuple]] = {
            k: (tuple(v["blk"]) + (k[0],) if v["blk"] else None)
            for k, v in facts.items()}
        changed = True
        while changed:
            changed = False
            for k, rcalls in calls.items():
                for _held, _raw, _line, tgts in rcalls:
                    for tgt in tgts:
                        if tgt not in facts:
                            continue
                        extra = trans_acq[tgt] - trans_acq[k]
                        if extra:
                            trans_acq[k] |= extra
                            changed = True
                        if trans_blk[k] is None \
                                and trans_blk[tgt] is not None:
                            trans_blk[k] = trans_blk[tgt]
                            changed = True

        # ordering edges: locks with an unresolvable identity (the
        # ``:?`` fallback scopes — object-attribute spellings like
        # ``req._lock``) are excluded; object lock flow is out of
        # scope by contract, and a speculative node would mint
        # deadlock reports between locks that may never coexist
        def orderable(lock: str) -> bool:
            return ":?" not in lock

        edges: Dict[Tuple[str, str], Tuple[int, str, str]] = {}
        for (rel, q), v in facts.items():
            for a, b, ln in v["edges"]:
                if orderable(a) and orderable(b):
                    edges.setdefault((a, b), (ln, rel, q))
            for held, _raw, line, tgts in calls[(rel, q)]:
                for tgt in tgts:
                    for b in trans_acq.get(tgt, ()):
                        for a in held:
                            if a != b and orderable(a) \
                                    and orderable(b):
                                edges.setdefault((a, b),
                                                 (line, rel, q))

        annotated: Set[str] = set()
        for rel, s in self.summaries.items():
            for key in s["ann_locks"]:
                annotated.add(canon(rel, key))
        return trans_acq, trans_blk, edges, annotated, facts, calls


# -- the summary cache -------------------------------------------------------

def analyzer_digest() -> str:
    """Hash of the analyzer's own sources: any rule/registry edit
    invalidates every cached summary and findings memo."""
    here = os.path.dirname(os.path.abspath(__file__))
    h = hashlib.sha256(str(SUMMARY_VERSION).encode())
    for fn in sorted(os.listdir(here)):
        if fn.endswith(".py"):
            with open(os.path.join(here, fn), "rb") as f:
                h.update(fn.encode())
                h.update(f.read())
    return h.hexdigest()[:16]


def content_hash(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()[:16]


class SummaryCache:
    """Content-hash-keyed per-file summaries plus a whole-run findings
    memo (``.veles-lint-cache.json``, gitignored — safe to delete at
    any time).  An edited file misses on its content hash and refreshes
    only its own entry; the findings memo keys on the digest of every
    (path, hash) pair plus the docs and analyzer digests, so any edit
    anywhere retires it without touching other files' summaries."""

    def __init__(self, path: Optional[str]):
        self.path = path
        self.digest = analyzer_digest()
        self.summaries: Dict[str, dict] = {}   # rel -> {hash, summary}
        self.findings: Optional[dict] = None   # {context, report}
        self.dirty = False
        if path and os.path.isfile(path):
            try:
                with open(path) as f:
                    doc = json.load(f)
                if doc.get("analyzer") == self.digest:
                    self.summaries = doc.get("files", {})
                    self.findings = doc.get("findings")
            except (ValueError, OSError):
                pass

    def summary(self, rel: str, h: str) -> Optional[dict]:
        entry = self.summaries.get(rel)
        if entry is not None and entry.get("hash") == h:
            return entry["summary"]
        return None

    def put_summary(self, rel: str, h: str, summary: dict):
        prev = self.summaries.get(rel)
        if prev is None or prev.get("hash") != h:
            self.summaries[rel] = {"hash": h, "summary": summary}
            self.dirty = True

    def context_digest(self, hashes: Dict[str, str],
                       docs_digest: str) -> str:
        h = hashlib.sha256(self.digest.encode())
        h.update(docs_digest.encode())
        for rel in sorted(hashes):
            h.update(f"{rel}={hashes[rel]}".encode())
        return h.hexdigest()[:16]

    def memo(self, context: str) -> Optional[dict]:
        if self.findings and self.findings.get("context") == context:
            return self.findings.get("report")
        return None

    def put_memo(self, context: str, report: dict):
        self.findings = {"context": context, "report": report}
        self.dirty = True

    def save(self):
        if not self.path or not self.dirty:
            return
        doc = {"comment": "veles-tpu-lint summary cache — content-hash "
                          "keyed, safe to delete (docs/analysis.md)",
               "analyzer": self.digest, "files": self.summaries,
               "findings": self.findings}
        tmp = self.path + ".tmp"
        try:
            with open(tmp, "w") as f:
                json.dump(doc, f, separators=(",", ":"))
            os.replace(tmp, self.path)
        except OSError:
            try:
                os.unlink(tmp)
            except OSError:
                pass
        self.dirty = False


def docs_digest(docs_dir: Optional[str]) -> str:
    """Hash of the doc files the drift rules read (VK303/VM4xx)."""
    if not docs_dir or not os.path.isdir(docs_dir):
        return "nodocs"
    h = hashlib.sha256()
    for base, _dirs, files in os.walk(docs_dir):
        for fn in sorted(files):
            if fn.endswith((".md", ".rst", ".txt")):
                try:
                    with open(os.path.join(base, fn), "rb") as f:
                        h.update(fn.encode())
                        h.update(f.read())
                except OSError:
                    pass
    return h.hexdigest()[:16]
