"""Data normalizer registry.

Re-design of the reference normalization module (reference:
veles/normalization.py:110-636 — NormalizerRegistry with stateful/stateless
normalizers: linear, range_linear, mean_disp, external_mean, exp, pointwise,
none; state serialized so inference can denormalize).

Normalizers here are numpy/host-side (they run in the loader's analysis pass
over the dataset, reference: veles/loader/base.py:755-803) and expose
``state()``/``set_state()`` so loader state lands in checkpoints.
"""

from __future__ import annotations

from typing import Dict, Optional, Type

import numpy as np


class NormalizerRegistry:
    _reg: Dict[str, Type["NormalizerBase"]] = {}

    @classmethod
    def register(cls, name):
        def deco(klass):
            cls._reg[name] = klass
            klass.MAPPING = name
            return klass
        return deco

    @classmethod
    def create(cls, name: str, **kwargs) -> "NormalizerBase":
        return cls._reg[name](**kwargs)

    @classmethod
    def names(cls):
        return sorted(cls._reg)


class NormalizerBase:
    """analyze(data) accumulates statistics; normalize(data) applies in
    place-free fashion; denormalize inverts (for inference-time output
    mapping, reference: veles/normalization.py state serialization)."""

    MAPPING = "base"

    def analyze(self, data: np.ndarray) -> None:
        pass

    def normalize(self, data: np.ndarray) -> np.ndarray:
        raise NotImplementedError

    def denormalize(self, data: np.ndarray) -> np.ndarray:
        raise NotImplementedError

    def state(self) -> dict:
        return {k: v for k, v in vars(self).items()
                if not k.startswith("_")}

    def set_state(self, st: dict) -> None:
        for k, v in st.items():
            setattr(self, k, v)


@NormalizerRegistry.register("none")
class NoneNormalizer(NormalizerBase):
    def normalize(self, data):
        return data

    def denormalize(self, data):
        return data


@NormalizerRegistry.register("linear")
class LinearNormalizer(NormalizerBase):
    """Scale each sample into [-1, 1] by per-dataset min/max."""

    def __init__(self, interval=(-1.0, 1.0)):
        self.interval = tuple(interval)
        self.vmin: Optional[float] = None
        self.vmax: Optional[float] = None

    def analyze(self, data):
        lo, hi = float(np.min(data)), float(np.max(data))
        self.vmin = lo if self.vmin is None else min(self.vmin, lo)
        self.vmax = hi if self.vmax is None else max(self.vmax, hi)

    def normalize(self, data):
        a, b = self.interval
        span = (self.vmax - self.vmin) or 1.0
        return (data.astype(np.float32) - self.vmin) / span * (b - a) + a

    def denormalize(self, data):
        a, b = self.interval
        span = (self.vmax - self.vmin) or 1.0
        return (data - a) / (b - a) * span + self.vmin


@NormalizerRegistry.register("range_linear")
class RangeLinearNormalizer(LinearNormalizer):
    """Linear with a fixed, known source range (e.g. uint8 images 0..255)."""

    def __init__(self, source_range=(0.0, 255.0), interval=(-1.0, 1.0)):
        super().__init__(interval)
        self.vmin, self.vmax = map(float, source_range)

    def analyze(self, data):
        pass


@NormalizerRegistry.register("mean_disp")
class MeanDispNormalizer(NormalizerBase):
    """(x - mean) / disp with dataset-wide statistics (reference:
    veles/mean_disp_normalizer.py + 'mean_disp' normalizer)."""

    def __init__(self):
        self._sum = None
        self._sumsq = None
        self._count = 0
        self.mean = None
        self.disp = None

    def analyze(self, data):
        d = data.astype(np.float64).reshape(len(data), -1)
        s = d.sum(axis=0)
        ss = np.square(d).sum(axis=0)
        if self._sum is None:
            self._sum, self._sumsq = s, ss
        else:
            self._sum = self._sum + s
            self._sumsq = self._sumsq + ss
        self._count += len(d)
        mean = self._sum / self._count
        var = np.maximum(self._sumsq / self._count - np.square(mean), 1e-12)
        self.mean = mean.astype(np.float32)
        self.disp = np.sqrt(var).astype(np.float32)

    def normalize(self, data):
        shape = data.shape
        flat = data.astype(np.float32).reshape(len(data), -1)
        return ((flat - self.mean) / self.disp).reshape(shape)

    def denormalize(self, data):
        shape = data.shape
        flat = data.reshape(len(data), -1)
        return (flat * self.disp + self.mean).reshape(shape)


@NormalizerRegistry.register("external_mean")
class ExternalMeanNormalizer(NormalizerBase):
    """Subtract a provided mean image (reference 'external_mean')."""

    def __init__(self, mean=None, scale=1.0):
        self.mean = None if mean is None else np.asarray(mean, np.float32)
        self.scale = scale

    def normalize(self, data):
        return (data.astype(np.float32) - self.mean) * self.scale

    def denormalize(self, data):
        return data / self.scale + self.mean


@NormalizerRegistry.register("exp")
class ExpNormalizer(NormalizerBase):
    """Sigmoid-ish squashing (reference 'exp')."""

    def normalize(self, data):
        return 1.0 / (1.0 + np.exp(-data.astype(np.float32)))

    def denormalize(self, data):
        d = np.clip(data, 1e-7, 1 - 1e-7)
        return np.log(d / (1.0 - d))


@NormalizerRegistry.register("pointwise")
class PointwiseNormalizer(NormalizerBase):
    """Per-feature linear mapping into [-1, 1] (reference 'pointwise')."""

    def __init__(self):
        self.vmin = None
        self.vmax = None

    def analyze(self, data):
        d = data.reshape(len(data), -1)
        lo, hi = d.min(axis=0), d.max(axis=0)
        self.vmin = lo if self.vmin is None else np.minimum(self.vmin, lo)
        self.vmax = hi if self.vmax is None else np.maximum(self.vmax, hi)

    def normalize(self, data):
        shape = data.shape
        d = data.astype(np.float32).reshape(len(data), -1)
        span = np.maximum(self.vmax - self.vmin, 1e-12)
        return ((d - self.vmin) / span * 2.0 - 1.0).reshape(shape)

    def denormalize(self, data):
        shape = data.shape
        d = data.reshape(len(data), -1)
        span = np.maximum(self.vmax - self.vmin, 1e-12)
        return ((d + 1.0) / 2.0 * span + self.vmin).reshape(shape)
