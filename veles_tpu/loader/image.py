"""Image loading pipeline.

Reference parity: the PIL-based image stack (reference:
veles/loader/image.py:106 ImageLoader — decode, scale, crop, mirror,
rotation, color space, background blending; file scanning with
auto-labeling from path regex, veles/loader/file_image.py:53-177;
fullbatch variant veles/loader/fullbatch_image.py:56).

TPU redesign: decoding/augment happens on host into numpy (the device gets
fixed-shape normalized batches); scale/crop/mirror keep the reference
semantics. Heavy random augmentation is deterministic per (epoch, index)
via the loader PRNG stream, so distributed shards and checkpoint resume
reproduce the exact pixel stream."""

from __future__ import annotations

import os
import re
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from .base import Loader, TEST, TRAIN, VALID


def _pil():
    from PIL import Image
    return Image


class ImageLoader(Loader):
    """Base for image loaders: decode → scale → crop → (mirror) → float.

    scale: (W, H) target;  crop: (W, H) center crop after scale;
    mirror: "random" | True | False;  grayscale: collapse channels;
    rotations: sequence of degrees to sample per train image (reference:
    rotation augmentation, veles/loader/image.py:106);
    background: None | float | array — fill revealed by rotation/crop
    (reference: background blending).
    """

    def __init__(self, scale: Tuple[int, int] = (32, 32),
                 crop: Optional[Tuple[int, int]] = None,
                 mirror=False, grayscale: bool = False,
                 rotations: Optional[Tuple[float, ...]] = None,
                 background=None, **kw):
        super().__init__(**kw)
        self.scale = tuple(scale)
        self.crop = tuple(crop) if crop else None
        self.mirror = mirror
        self.grayscale = grayscale
        self.rotations = tuple(rotations) if rotations else None
        self.background = background

    # -- subclass contract: sample keys ------------------------------------
    def get_image_paths(self, klass: int) -> List[str]:
        raise NotImplementedError

    def get_label(self, path: str) -> int:
        raise NotImplementedError

    # -- pipeline ----------------------------------------------------------
    def decode(self, path: str) -> np.ndarray:
        Image = _pil()
        with Image.open(path) as im:
            im = im.convert("L" if self.grayscale else "RGB")
            im = im.resize(self.scale, Image.BILINEAR)
            arr = np.asarray(im, np.float32)
        if self.grayscale:
            arr = arr[..., None]
        return arr

    def _bg_value(self, arr: np.ndarray):
        if self.background is None:
            return 0.0
        if np.isscalar(self.background):
            return float(self.background)
        return np.asarray(self.background, np.float32)

    def augment(self, arr: np.ndarray, index: int, epoch: int,
                klass: int) -> np.ndarray:
        if self.rotations and klass == TRAIN:
            rng = np.random.Generator(np.random.PCG64(
                [self.subset_seed, epoch, index, 0x207A7E]))
            deg = float(self.rotations[rng.integers(len(self.rotations))])
            if deg:
                Image = _pil()
                bg = self._bg_value(arr)
                if arr.ndim == 3 and arr.shape[-1] == 3:
                    # broadcast a scalar to all 3 channels — a 1-tuple
                    # fillcolor would paint (bg, 0, 0)
                    fill = tuple(int(v) for v in np.broadcast_to(
                        np.atleast_1d(bg), (3,)))
                else:
                    fill = int(np.mean(bg))
                im = Image.fromarray(arr.astype(np.uint8).squeeze())
                im = im.rotate(deg, resample=Image.BILINEAR,
                               fillcolor=fill)
                arr = np.asarray(im, np.float32)
                if arr.ndim == 2:
                    arr = arr[..., None]
        if self.crop:
            cw, ch = self.crop
            h, w = arr.shape[:2]
            y0, x0 = (h - ch) // 2, (w - cw) // 2
            arr = arr[y0:y0 + ch, x0:x0 + cw]
        do_mirror = self.mirror is True
        if self.mirror == "random" and klass == TRAIN:
            # deterministic per (epoch, index): resume-stable augmentation
            rng = np.random.Generator(np.random.PCG64(
                [self.subset_seed, epoch, index, 0x51DE]))
            do_mirror = bool(rng.integers(2))
        if do_mirror:
            arr = arr[:, ::-1]
        return arr

    # -- Loader contract ----------------------------------------------------
    def load_data(self):
        self._paths = {k: self.get_image_paths(k)
                       for k in (TEST, VALID, TRAIN)}
        self._labels = {k: np.asarray([self.get_label(p)
                                       for p in v], np.int32)
                        for k, v in self._paths.items()}
        for k in (TEST, VALID, TRAIN):
            self.class_lengths[k] = len(self._paths[k])

    def fill_minibatch(self, indices, klass):
        imgs = []
        for idx in indices:
            arr = self.decode(self._paths[klass][int(idx)])
            arr = self.augment(arr, int(idx), self.epoch_number, klass)
            imgs.append(arr)
        batch = {"@input": np.stack(imgs)}
        if len(self._labels[klass]):
            batch["@labels"] = self._labels[klass][indices]
        return batch


class FileImageLoader(ImageLoader):
    """Directory-scanning image loader with auto-labels from a path regex
    (reference: veles/loader/file_image.py — AutoLabelFileImageLoader).

    train_paths/valid_paths/test_paths: directories to walk;
    label_regexp: first group = label string; label mapping is sorted
    strings -> dense ints (reference label-mapping behavior,
    veles/loader/base.py:925+)."""

    EXTENSIONS = {".png", ".jpg", ".jpeg", ".bmp", ".gif", ".tif"}

    def __init__(self, train_paths: Sequence[str] = (),
                 valid_paths: Sequence[str] = (),
                 test_paths: Sequence[str] = (),
                 label_regexp: str = r"/([^/]+)/[^/]+$", **kw):
        super().__init__(**kw)
        self._dirs = {TRAIN: list(train_paths), VALID: list(valid_paths),
                      TEST: list(test_paths)}
        self.label_regexp = re.compile(label_regexp)
        self.label_mapping: Dict[str, int] = {}

    def get_image_paths(self, klass: int) -> List[str]:
        found = []
        for d in self._dirs[klass]:
            for base, _, files in sorted(os.walk(d)):
                for fn in sorted(files):
                    if os.path.splitext(fn)[1].lower() in self.EXTENSIONS:
                        found.append(os.path.join(base, fn))
        return found

    def load_data(self):
        super().load_data()
        raw = set()
        for k in (TEST, VALID, TRAIN):
            for p in self._paths[k]:
                m = self.label_regexp.search(p)
                raw.add(m.group(1) if m else "")
        self.label_mapping = {s: i for i, s in enumerate(sorted(raw))}
        for k in (TEST, VALID, TRAIN):
            labs = []
            for p in self._paths[k]:
                m = self.label_regexp.search(p)
                labs.append(self.label_mapping[m.group(1) if m else ""])
            self._labels[k] = np.asarray(labs, np.int32)

    def get_label(self, path: str) -> int:
        return 0  # replaced in load_data by the mapped labels


class Hdf5Loader(Loader):
    """HDF5 dataset loader (reference: veles/loader/loader_hdf5.py:48-151 —
    datasets named by class with data/labels pairs)."""

    def __init__(self, files: Dict[int, str], data_key: str = "data",
                 labels_key: str = "labels", **kw):
        super().__init__(**kw)
        self._files = dict(files)
        self.data_key = data_key
        self.labels_key = labels_key
        self._h5: Dict[int, object] = {}

    def load_data(self):
        import h5py
        for k, path in self._files.items():
            f = h5py.File(path, "r")
            self._h5[k] = f
            self.class_lengths[k] = len(f[self.data_key])

    def fill_minibatch(self, indices, klass):
        f = self._h5[klass]
        order = np.argsort(indices)  # h5py wants increasing indices
        inv = np.argsort(order)
        sorted_idx = np.asarray(indices)[order]
        # h5py fancy indexing requires strictly increasing unique indices;
        # fall back to per-row reads when padding duplicated indices.
        if len(np.unique(sorted_idx)) == len(sorted_idx):
            data = f[self.data_key][sorted_idx][inv]
            batch = {"@input": np.asarray(data, np.float32)}
            if self.labels_key in f:
                batch["@labels"] = np.asarray(
                    f[self.labels_key][sorted_idx][inv], np.int32)
        else:
            data = np.stack([f[self.data_key][int(i)] for i in indices])
            batch = {"@input": np.asarray(data, np.float32)}
            if self.labels_key in f:
                batch["@labels"] = np.asarray(
                    [f[self.labels_key][int(i)] for i in indices], np.int32)
        return batch
