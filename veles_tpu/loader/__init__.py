from .base import (TEST, VALID, TRAIN, CLASS_NAMES, Loader, ArrayLoader,
                   LoaderError)
from .fullbatch import FullBatchLoader
