from .base import (TEST, VALID, TRAIN, CLASS_NAMES, Loader, ArrayLoader,
                   LoaderError)
from .fullbatch import FullBatchAugmentedLoader, FullBatchLoader
from .image import FileImageLoader, Hdf5Loader, ImageLoader
from .interactive import QueueLoader
from .saver import MinibatchesLoader, MinibatchesSaver
from .ext import (CsvLoader, EnsembleResultsLoader, PicklesLoader,
                  WavLoader, read_wav)
from .hdfs import HdfsTextLoader, WebHdfsClient
