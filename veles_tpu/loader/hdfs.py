"""HDFS text loading over the WebHDFS REST gateway.

Reference parity: ``HDFSTextLoader`` (reference:
veles/loader/hdfs_loader.py:48) streamed chunks of text lines off HDFS via
the snakebite native-RPC client. That client (and a namenode to talk to)
isn't available here, so this redesign speaks **WebHDFS** — the standard
HTTP gateway every Hadoop distribution ships — with nothing but stdlib
urllib. The protocol is two-step: the namenode answers metadata ops
directly and redirects OPEN reads to a datanode with a 307
(urllib follows it transparently).

Capabilities kept from the reference unit:
* ``stat`` on initialize (logged, validates the path exists);
* streamed line iteration — the file is read in byte ranges, never fully
  resident;
* chunked output: ``read_chunks()`` yields lists of ``chunk_lines`` lines
  with a ``finished`` flag, exactly the reference's output contract.

``CsvLoader`` accepts ``webhdfs://host:port/path`` sources through this
client (see ext.py), closing the round-1 "HDFS loader absent" gap.
"""

from __future__ import annotations

import json
import urllib.error
import urllib.parse
import urllib.request
from typing import Dict, Iterator, List, Optional

from ..logger import Logger
from .base import LoaderError


def _urlopen_retrying(url: str, timeout: float):
    """urlopen with bounded transient retry (connection errors and 5xx —
    a datanode mid-restart — back off and retry; 4xx like a missing path
    fail fast).  The loader-level ``_fetch_batch`` retry can't see these
    because this client wraps them into LoaderError for its callers.
    Retry shape is the shared ``deploy.http_retry`` (backoff + jitter),
    bounded by the LOADER knobs rather than the serving ones."""
    from ..config import root
    from ..runtime.deploy import http_retry
    return http_retry(
        lambda: urllib.request.urlopen(url, timeout=timeout),
        what=f"WebHDFS {url.split('?', 1)[0]}",
        retries=int(root.common.loader.get("retries", 2)),
        base_s=float(root.common.loader.get("retry_backoff_s", 0.05)))


class WebHdfsClient:
    """Minimal WebHDFS v1 client (GETFILESTATUS / LISTSTATUS / OPEN)."""

    def __init__(self, url: str, user: Optional[str] = None,
                 timeout: float = 30.0):
        # url: "http://namenode:9870" (or "webhdfs://namenode:9870")
        if url.startswith("webhdfs://"):
            url = "http://" + url[len("webhdfs://"):]
        self.base = url.rstrip("/")
        self.user = user
        self.timeout = timeout

    def _url(self, path: str, op: str, **params) -> str:
        if not path.startswith("/"):
            path = "/" + path
        q = {"op": op, **params}
        if self.user:
            q["user.name"] = self.user
        return (f"{self.base}/webhdfs/v1"
                f"{urllib.parse.quote(path)}?{urllib.parse.urlencode(q)}")

    def _get_json(self, url: str) -> dict:
        try:
            with _urlopen_retrying(url, self.timeout) as r:
                return json.load(r)
        except urllib.error.HTTPError as e:
            raise LoaderError(
                f"WebHDFS {e.code} for {url}: "
                f"{e.read(200)!r}") from e

    def stat(self, path: str) -> dict:
        return self._get_json(self._url(path, "GETFILESTATUS"))[
            "FileStatus"]

    def list(self, path: str) -> List[dict]:
        return self._get_json(self._url(path, "LISTSTATUS"))[
            "FileStatuses"]["FileStatus"]

    def open(self, path: str, offset: int = 0,
             length: Optional[int] = None) -> bytes:
        params: Dict[str, int] = {}
        if offset:
            params["offset"] = offset
        if length is not None:
            params["length"] = length
        url = self._url(path, "OPEN", **params)
        try:
            # The namenode 307-redirects to a datanode; urllib follows.
            with _urlopen_retrying(url, self.timeout) as r:
                return r.read()
        except urllib.error.HTTPError as e:
            raise LoaderError(
                f"WebHDFS OPEN failed ({e.code}) for {path}") from e

    def text(self, path: str, encoding: str = "utf-8",
             block: int = 1 << 20) -> Iterator[str]:
        """Stream decoded lines without holding the whole file."""
        size = int(self.stat(path)["length"])
        buf = b""
        offset = 0
        while offset < size:
            chunk = self.open(path, offset=offset,
                              length=min(block, size - offset))
            if not chunk:
                break
            offset += len(chunk)
            buf += chunk
            *lines, buf = buf.split(b"\n")
            for ln in lines:
                yield ln.decode(encoding)
        if buf:
            yield buf.decode(encoding)


class HdfsTextLoader(Logger):
    """Chunked HDFS text reader (the reference unit's contract: fill
    ``output`` with ``chunk_lines`` lines per run until ``finished``)."""

    def __init__(self, url: str, file: str, chunk_lines: int = 1000,
                 user: Optional[str] = None):
        self.client = WebHdfsClient(url, user=user)
        self.file = file
        self.chunk_lines = int(chunk_lines)
        self.finished = False
        self._gen: Optional[Iterator[str]] = None

    def initialize(self) -> None:
        st = self.client.stat(self.file)
        self.debug("opened %s (%d bytes)", self.file, st["length"])
        self._gen = self.client.text(self.file)
        self.finished = False

    def read_chunk(self) -> List[str]:
        """Next chunk of up to ``chunk_lines`` lines; sets ``finished``
        when the file is exhausted."""
        if self._gen is None:
            self.initialize()
        out: List[str] = []
        for _ in range(self.chunk_lines):
            try:
                out.append(next(self._gen))
            except StopIteration:
                self.finished = True
                break
        return out

    def read_chunks(self) -> Iterator[List[str]]:
        while not self.finished:
            chunk = self.read_chunk()
            if chunk:
                yield chunk
