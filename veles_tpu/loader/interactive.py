"""Streaming / interactive loaders.

Reference parity:
* ``InteractiveLoader`` (reference: veles/loader/interactive.py:57 — feed()
  samples from a REPL into a running workflow),
* ``ZeroMQLoader`` (reference: veles/zmq_loader.py:74-138 — ROUTER-socket
  job queue on slaves).

TPU redesign: a thread-safe queue loader covers both — producers call
``feed()`` from any thread (REPL, HTTP handler, socket reader); the
training/inference loop consumes fixed-size batches. The ZMQ transport
itself is dropped (SPMD needs no job sockets); network feeding composes as
"HTTP server thread -> QueueLoader.feed"."""

from __future__ import annotations

import queue
import threading
from typing import Dict, Iterator, Optional

import numpy as np

from .base import Loader, TRAIN


class QueueLoader(Loader):
    """Serve batches from a thread-safe queue of fed samples."""

    def __init__(self, input_shape, minibatch_size=1, *, maxsize: int = 0,
                 **kw):
        super().__init__(minibatch_size=minibatch_size, **kw)
        self.input_shape = tuple(input_shape)
        self._q: "queue.Queue" = queue.Queue(maxsize=maxsize)
        self._closed = threading.Event()

    def feed(self, sample, label: Optional[int] = None) -> None:
        """Enqueue one sample (thread-safe)."""
        arr = np.asarray(sample, np.float32)
        if arr.shape != self.input_shape:
            raise ValueError(
                f"sample shape {arr.shape} != {self.input_shape}")
        self._q.put((arr, label))

    def close(self) -> None:
        """No more samples; pending partial batch is flushed padded."""
        self._closed.set()
        self._q.put(None)  # wake the consumer

    def load_data(self):
        # Unbounded stream: lengths unknown; report one pseudo-sample so
        # initialize() passes (the reference's interactive loader did the
        # same trick with a fake single-sample epoch).
        self.class_lengths = [0, 0, self.minibatch_size]

    def fill_minibatch(self, indices, klass):
        raise NotImplementedError("QueueLoader serves from the queue")

    def iter_epoch(self, klass: int, epoch=None
                   ) -> Iterator[Dict[str, np.ndarray]]:
        if klass != TRAIN:
            return
        if self._closed.is_set() and self._q.empty():
            return  # stream ended; later epochs must not block on get()
        bs = self.minibatch_size
        buf, labels = [], []
        while True:
            item = self._q.get()
            if item is None:
                # re-arm the sentinel so a subsequent iter_epoch (or a
                # concurrent consumer) also terminates instead of blocking
                self._q.put(None)
                break
            buf.append(item[0])
            labels.append(item[1] if item[1] is not None else 0)
            if len(buf) == bs:
                yield self._emit(buf, labels, bs)
                buf, labels = [], []
            if self._closed.is_set() and self._q.empty():
                break
        if buf:
            yield self._emit(buf, labels, bs)

    def _emit(self, buf, labels, bs):
        valid = len(buf)
        while len(buf) < bs:
            buf.append(np.zeros(self.input_shape, np.float32))
            labels.append(0)
        mask = np.zeros(bs, np.float32)
        mask[:valid] = 1.0
        return {"@input": np.stack(buf),
                "@labels": np.asarray(labels, np.int32),
                "@mask": mask}


class SocketLoader(QueueLoader):
    """Network job queue: a TCP listener feeds the queue with sample
    frames (reference: ZeroMQLoader's ROUTER socket job queue,
    veles/zmq_loader.py:74-138 — the Mastodon/Hadoop contact point).

    Frames use the package's length-prefixed framing with the pickle-free
    ``veles_tpu.wire`` serializer (JSON header + raw array bytes): each
    frame is ``{"input": array, "label": int?}`` or ``{"kind": "close"}``
    to end the stream.  A hostile peer can inject bogus samples but never
    execute code — unlike the reference's pickled ZMQ payloads."""

    def __init__(self, input_shape, minibatch_size=1, *, port: int = 0,
                 host: str = "127.0.0.1", **kw):
        super().__init__(input_shape, minibatch_size, **kw)
        import socket as _socket
        self._listener = _socket.socket(_socket.AF_INET,
                                        _socket.SOCK_STREAM)
        self._listener.setsockopt(_socket.SOL_SOCKET,
                                  _socket.SO_REUSEADDR, 1)
        self._listener.bind((host, port))
        self._listener.listen(8)
        self.endpoint = "tcp://%s:%d" % self._listener.getsockname()[:2]
        self._accept_thread = threading.Thread(target=self._accept_loop,
                                               daemon=True)
        self._accept_thread.start()

    def _accept_loop(self):
        import socket as _socket
        while not self._closed.is_set():
            try:
                self._listener.settimeout(0.2)
                conn, _ = self._listener.accept()
            except _socket.timeout:
                continue
            except OSError:
                return
            threading.Thread(target=self._reader, args=(conn,),
                             daemon=True).start()

    def _reader(self, conn):
        from ..graphics import recv_frame
        try:
            while not self._closed.is_set():
                try:
                    frame = recv_frame(conn)
                except OSError:
                    break
                except Exception as e:
                    # Corrupt pickle body / hostile length prefix: drop the
                    # whole connection (frame boundary is lost) but never
                    # kill the reader silently.
                    self.warning("dropping connection on bad frame: %s", e)
                    break
                if frame is None:
                    break
                if not isinstance(frame, dict):
                    self.warning("non-dict frame dropped: %r",
                                 type(frame).__name__)
                    continue
                if frame.get("kind") == "close":
                    self.close()
                    break
                try:
                    self.feed(frame["input"], frame.get("label"))
                except (ValueError, KeyError, TypeError) as e:
                    self.warning("bad frame dropped: %s", e)
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def close(self):
        super().close()
        try:
            self._listener.close()
        except OSError:
            pass


def feed_socket(endpoint: str, samples, labels=None, *,
                close: bool = False) -> None:
    """Producer-side helper: push samples to a SocketLoader endpoint."""
    import socket as _socket
    from .. import wire
    from ..graphics import _send_frame  # single source of the framing
    assert endpoint.startswith("tcp://"), endpoint
    host, _, port = endpoint[6:].partition(":")
    sock = _socket.create_connection((host, int(port)))
    try:
        for i, sample in enumerate(samples):
            frame = {"input": np.asarray(sample, np.float32)}
            if labels is not None:
                frame["label"] = int(labels[i])
            data = wire.dumps(frame)
            if len(data) > wire.MAX_FRAME:
                # The receiving SocketLoader caps frames at MAX_FRAME and
                # drops the connection on violation — which would silently
                # discard every later sample; fail loudly at the producer.
                raise ValueError(
                    f"sample {i} serializes to {len(data)} bytes, over the "
                    f"wire frame cap ({wire.MAX_FRAME})")
            _send_frame(sock, data)
        if close:
            _send_frame(sock, wire.dumps({"kind": "close"}))
    finally:
        sock.close()
