"""Streaming / interactive loaders.

Reference parity:
* ``InteractiveLoader`` (reference: veles/loader/interactive.py:57 — feed()
  samples from a REPL into a running workflow),
* ``ZeroMQLoader`` (reference: veles/zmq_loader.py:74-138 — ROUTER-socket
  job queue on slaves).

TPU redesign: a thread-safe queue loader covers both — producers call
``feed()`` from any thread (REPL, HTTP handler, socket reader); the
training/inference loop consumes fixed-size batches. The ZMQ transport
itself is dropped (SPMD needs no job sockets); network feeding composes as
"HTTP server thread -> QueueLoader.feed"."""

from __future__ import annotations

import queue
import threading
from typing import Dict, Iterator, Optional

import numpy as np

from .base import Loader, TRAIN


class QueueLoader(Loader):
    """Serve batches from a thread-safe queue of fed samples."""

    def __init__(self, input_shape, minibatch_size=1, *, maxsize: int = 0,
                 **kw):
        super().__init__(minibatch_size=minibatch_size, **kw)
        self.input_shape = tuple(input_shape)
        self._q: "queue.Queue" = queue.Queue(maxsize=maxsize)
        self._closed = threading.Event()

    def feed(self, sample, label: Optional[int] = None) -> None:
        """Enqueue one sample (thread-safe)."""
        arr = np.asarray(sample, np.float32)
        if arr.shape != self.input_shape:
            raise ValueError(
                f"sample shape {arr.shape} != {self.input_shape}")
        self._q.put((arr, label))

    def close(self) -> None:
        """No more samples; pending partial batch is flushed padded."""
        self._closed.set()
        self._q.put(None)  # wake the consumer

    def load_data(self):
        # Unbounded stream: lengths unknown; report one pseudo-sample so
        # initialize() passes (the reference's interactive loader did the
        # same trick with a fake single-sample epoch).
        self.class_lengths = [0, 0, self.minibatch_size]

    def fill_minibatch(self, indices, klass):
        raise NotImplementedError("QueueLoader serves from the queue")

    def iter_epoch(self, klass: int, epoch=None
                   ) -> Iterator[Dict[str, np.ndarray]]:
        if klass != TRAIN:
            return
        if self._closed.is_set() and self._q.empty():
            return  # stream ended; later epochs must not block on get()
        bs = self.minibatch_size
        buf, labels = [], []
        while True:
            item = self._q.get()
            if item is None:
                # re-arm the sentinel so a subsequent iter_epoch (or a
                # concurrent consumer) also terminates instead of blocking
                self._q.put(None)
                break
            buf.append(item[0])
            labels.append(item[1] if item[1] is not None else 0)
            if len(buf) == bs:
                yield self._emit(buf, labels, bs)
                buf, labels = [], []
            if self._closed.is_set() and self._q.empty():
                break
        if buf:
            yield self._emit(buf, labels, bs)

    def _emit(self, buf, labels, bs):
        valid = len(buf)
        while len(buf) < bs:
            buf.append(np.zeros(self.input_shape, np.float32))
            labels.append(0)
        mask = np.zeros(bs, np.float32)
        mask[:valid] = 1.0
        return {"@input": np.stack(buf),
                "@labels": np.asarray(labels, np.int32),
                "@mask": mask}
