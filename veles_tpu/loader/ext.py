"""Extended loader family: pickles, audio, CSV/text, ensemble results.

Reference parity (SURVEY.md §2.4):
* ``PicklesLoader``  — dataset from pickled arrays, one pickle per class
  (reference: veles/loader/pickles.py:55).
* ``WavLoader``      — audio windows + labels from WAV files. The reference
  used a libsndfile FFI binding (veles/loader/libsndfile.py:42-91,
  libsndfile_loader.py:46-107); here stdlib ``wave`` decodes PCM WAV — no
  native dependency — and the features are fixed-size windows (optionally
  magnitude spectra via an rFFT, replacing the reference's external DSP).
* ``CsvLoader``      — delimited-text rows -> (features, label) arrays. The
  reference's HDFS text loader (veles/loader/hdfs_loader.py:48) parsed the
  same line format streamed from HDFS; ``hdfs://`` URLs raise a clear
  gating error here (no hadoop client in this environment) while local
  paths and open file objects work the same.
* ``EnsembleResultsLoader`` — reads the per-model results JSON written by
  ensemble training for ensemble test mode (reference:
  veles/loader/ensemble.py:53-143, consuming the JSON produced by
  veles/ensemble/model_workflow.py).
"""

from __future__ import annotations

import json
import os
import pickle
import wave
from urllib.parse import urlparse
from typing import Dict, List, Optional, Sequence

import numpy as np

from .base import TEST, TRAIN, VALID, Loader, LoaderError


class PicklesLoader(Loader):
    """Dataset from pickle files, one path per class (test/valid/train).

    Each pickle holds either an (N, ...) array, or a dict with
    ``data``/``labels`` (and optionally ``targets``) keys, matching the
    shapes ArrayLoader expects.
    """

    def __init__(self, paths: Dict[int, str], normalizer=None, **kw):
        super().__init__(**kw)
        self._paths = dict(paths)
        self.normalizer = normalizer
        self._data: Dict[int, np.ndarray] = {}
        self._labels: Dict[int, Optional[np.ndarray]] = {}
        self._targets: Dict[int, Optional[np.ndarray]] = {}

    def load_data(self):
        for klass in (TEST, VALID, TRAIN):
            path = self._paths.get(klass)
            if not path:
                continue
            with open(path, "rb") as f:
                obj = pickle.load(f)
            if isinstance(obj, dict):
                data = np.asarray(obj["data"])
                labels = obj.get("labels")
                targets = obj.get("targets")
            else:
                data, labels, targets = np.asarray(obj), None, None
            self._data[klass] = data
            self._labels[klass] = (None if labels is None
                                   else np.asarray(labels))
            self._targets[klass] = (None if targets is None
                                    else np.asarray(targets))
            self.class_lengths[klass] = len(data)
        if self.normalizer is not None and TRAIN in self._data:
            self.normalizer.analyze(self._data[TRAIN])
            for klass in list(self._data):
                self._data[klass] = self.normalizer.normalize(
                    self._data[klass])

    def fill_minibatch(self, indices, klass):
        batch = {"@input": self._data[klass][indices]}
        if self._labels.get(klass) is not None:
            batch["@labels"] = self._labels[klass][indices]
        if self._targets.get(klass) is not None:
            batch["@targets"] = self._targets[klass][indices]
        return batch


def read_wav(path: str) -> tuple:
    """Decode a PCM WAV file to (float32 mono samples in [-1, 1], rate)."""
    with wave.open(path, "rb") as w:
        n = w.getnframes()
        width = w.getsampwidth()
        channels = w.getnchannels()
        rate = w.getframerate()
        raw = w.readframes(n)
    if width == 1:  # unsigned 8-bit
        x = (np.frombuffer(raw, np.uint8).astype(np.float32) - 128.0) / 128.0
    elif width == 2:
        x = np.frombuffer(raw, "<i2").astype(np.float32) / 32768.0
    elif width == 4:
        x = np.frombuffer(raw, "<i4").astype(np.float32) / 2147483648.0
    else:
        raise LoaderError(f"unsupported WAV sample width {width}")
    if channels > 1:
        x = x.reshape(-1, channels).mean(axis=1)
    return x, rate


class WavLoader(Loader):
    """Fixed-size windows from labeled WAV files.

    ``files[klass]`` is a list of (path, label) pairs. Each file is cut into
    non-overlapping windows of ``window`` samples; ``spectrum=True`` maps
    each window to its rFFT magnitude (window//2+1 features), which is both
    the idiomatic audio frontend and a shape XLA pads nicely to lanes.
    """

    def __init__(self, files: Dict[int, Sequence], window: int = 1024,
                 spectrum: bool = False, **kw):
        super().__init__(**kw)
        self._files = files
        self.window = int(window)
        self.spectrum = bool(spectrum)
        self._data: Dict[int, np.ndarray] = {}
        self._labels: Dict[int, np.ndarray] = {}

    def load_data(self):
        for klass, entries in self._files.items():
            feats: List[np.ndarray] = []
            labels: List[int] = []
            for path, label in entries:
                samples, _rate = read_wav(path)
                n_win = len(samples) // self.window
                if n_win == 0:
                    continue
                wins = samples[:n_win * self.window].reshape(
                    n_win, self.window)
                if self.spectrum:
                    wins = np.abs(np.fft.rfft(wins, axis=1)).astype(
                        np.float32)
                feats.append(wins.astype(np.float32))
                labels.extend([label] * n_win)
            if feats:
                self._data[klass] = np.concatenate(feats, axis=0)
                self._labels[klass] = np.asarray(labels, np.int32)
                self.class_lengths[klass] = len(self._labels[klass])

    def fill_minibatch(self, indices, klass):
        return {"@input": self._data[klass][indices],
                "@labels": self._labels[klass][indices]}


class CsvLoader(Loader):
    """Delimited text -> float feature rows, optional label column.

    ``sources[klass]`` is a filesystem path, an open text-file object, or a
    ``webhdfs://namenode:port/path`` URL read through the WebHDFS REST
    gateway (loader/hdfs.py — the rebuild of the reference's snakebite
    HDFS loader, veles/loader/hdfs_loader.py:48). Bare ``hdfs://`` (native
    RPC) stays gated with an explanatory error pointing at webhdfs.
    """

    def __init__(self, sources: Dict[int, object], delimiter: str = ",",
                 label_column: Optional[int] = -1, skip_header: bool = False,
                 normalizer=None, **kw):
        super().__init__(**kw)
        self._sources = dict(sources)
        self.delimiter = delimiter
        self.label_column = label_column
        self.skip_header = bool(skip_header)
        self.normalizer = normalizer
        self._data: Dict[int, np.ndarray] = {}
        self._labels: Dict[int, Optional[np.ndarray]] = {}

    def _read_rows(self, src) -> List[List[str]]:
        if isinstance(src, str):
            if src.startswith("webhdfs://"):
                from .hdfs import WebHdfsClient
                u = urlparse(src)
                lines = list(WebHdfsClient(
                    f"http://{u.netloc}").text(u.path))
            elif src.startswith("hdfs://"):
                raise LoaderError(
                    "hdfs:// (native RPC) needs a hadoop client; use a "
                    "webhdfs://namenode:port/path URL instead (WebHDFS "
                    "REST gateway, loader/hdfs.py; reference analog: "
                    "veles/loader/hdfs_loader.py)")
            else:
                with open(src, "r") as f:
                    lines = f.read().splitlines()
        else:
            lines = src.read().splitlines()
        if self.skip_header and lines:
            lines = lines[1:]
        return [ln.split(self.delimiter) for ln in lines if ln.strip()]

    def load_data(self):
        for klass, src in self._sources.items():
            rows = self._read_rows(src)
            if not rows:
                continue
            if self.label_column is not None:
                lc = self.label_column % len(rows[0])
                labels = np.asarray([r[lc] for r in rows])
                try:
                    labels = labels.astype(np.int32)
                except ValueError:  # string labels -> dense int mapping
                    _, labels = np.unique(labels, return_inverse=True)
                    labels = labels.astype(np.int32)
                feats = [[v for i, v in enumerate(r) if i != lc]
                         for r in rows]
                self._labels[klass] = labels
            else:
                feats = rows
                self._labels[klass] = None
            self._data[klass] = np.asarray(feats, np.float32)
            self.class_lengths[klass] = len(rows)
        if self.normalizer is not None and TRAIN in self._data:
            self.normalizer.analyze(self._data[TRAIN])
            for klass in list(self._data):
                self._data[klass] = self.normalizer.normalize(
                    self._data[klass])

    def fill_minibatch(self, indices, klass):
        batch = {"@input": self._data[klass][indices]}
        if self._labels.get(klass) is not None:
            batch["@labels"] = self._labels[klass][indices]
        return batch


class EnsembleResultsLoader(Loader):
    """Serves per-model prediction matrices recorded during ensemble training
    for the ensemble-test vote (reference: veles/loader/ensemble.py:53-143).

    The manifest JSON is a list of per-model entries with ``results_path``
    pointing at an .npz of ``probabilities`` (N, n_classes) and ``labels``
    (N,). The served "@input" is the concatenation of all models'
    probabilities per sample — the input of a stacking/vote evaluator.
    """

    def __init__(self, manifest_path: str, klass: int = TEST, **kw):
        super().__init__(**kw)
        self.manifest_path = manifest_path
        self.klass = klass
        self._data: Optional[np.ndarray] = None
        self._labels: Optional[np.ndarray] = None

    def load_data(self):
        with open(self.manifest_path) as f:
            manifest = json.load(f)
        entries = manifest["models"] if isinstance(manifest, dict) \
            else manifest
        probs, labels = [], None
        base = os.path.dirname(os.path.abspath(self.manifest_path))
        for entry in entries:
            path = entry["results_path"]
            if not os.path.isabs(path):
                path = os.path.join(base, path)
            with np.load(path) as z:
                probs.append(z["probabilities"].astype(np.float32))
                if labels is None and "labels" in z:
                    labels = z["labels"].astype(np.int32)
        if not probs:
            raise LoaderError(f"no model results in {self.manifest_path}")
        lengths = {p.shape[0] for p in probs}
        if len(lengths) > 1:
            # Rows pair per-sample across models; differing counts mean
            # the models were evaluated on different sample sets and the
            # vote would silently mix samples.
            raise LoaderError(
                f"model result row counts differ ({sorted(lengths)}); "
                "all models must be evaluated on the same samples")
        n = lengths.pop()
        if labels is not None and labels.shape[0] != n:
            raise LoaderError(
                f"labels length {labels.shape[0]} != result rows {n}; "
                "labels must pair one-to-one with model predictions")
        self._data = np.concatenate(probs, axis=1)
        self._labels = labels
        self.class_lengths[self.klass] = n

    def fill_minibatch(self, indices, klass):
        batch = {"@input": self._data[indices]}
        if self._labels is not None:
            batch["@labels"] = self._labels[indices]
        return batch
