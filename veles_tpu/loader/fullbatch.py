"""Fullbatch loader: whole dataset resident in device HBM, minibatch gather
on device.

Reference parity: veles/loader/fullbatch.py:79 — dataset uploaded to device
memory once, minibatches gathered by a fill_minibatch_data_labels kernel
(ocl/fullbatch_loader.cl) from shuffled indices; graceful host fallback on
OOM (:164-242).

TPU redesign: the dataset lives as jax Arrays in HBM; the gather is
``jnp.take(data, idx, axis=0)`` inside a tiny jitted function — only the
*indices* cross the host→device boundary each step (the exact analog of the
reference's ship-indices-only distributed protocol,
veles/loader/base.py:631-639). On HBM-overflow the loader transparently
degrades to host-side gather (ArrayLoader behavior), mirroring the
reference's OOM fallback.
"""

from __future__ import annotations

from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .base import ArrayLoader, TEST, TRAIN, VALID


class FullBatchLoader(ArrayLoader):
    """ArrayLoader whose gather happens on device."""

    def __init__(self, *args, device=None, force_host: bool = False,
                 use_pallas_gather: Optional[bool] = None, **kw):
        super().__init__(*args, **kw)
        self._device = device
        self._force_host = force_host
        self._use_pallas_gather = use_pallas_gather
        self._dev_data: Dict[int, dict] = {}
        self._gather = None
        self.on_device = False

    def initialize(self):
        super().initialize()
        if self._force_host:
            return
        try:
            self._upload()
            self.on_device = True
            return
        except (RuntimeError, jax.errors.JaxRuntimeError) as e:
            self._dev_data.clear()
            if self._use_pallas_gather is not True:
                # gather is plain jnp.take (no packed layout) — a retry
                # without packing would re-run a byte-identical upload.
                err = e
            else:
                # The packed-gather layout pads rows; if that padding is
                # what overflowed HBM, retry unpacked before giving up
                # device residency entirely.
                self.warning("device upload failed (%s); retrying without "
                             "packed gather", e)
                try:
                    self._upload(allow_pallas=False)
                    self.on_device = True
                    return
                except (RuntimeError, jax.errors.JaxRuntimeError) as e2:
                    err = e2
        # OOM fallback (reference: veles/loader/fullbatch.py:164-242).
        self.warning("device upload failed (%s); host-side gather", err)
        self._dev_data.clear()
        self.on_device = False

    def _upload(self, allow_pallas: bool = True):
        put = (lambda x: jax.device_put(x, self._device)) \
            if self._device is not None else jax.device_put
        for klass in (TEST, VALID, TRAIN):
            if self.class_lengths[klass] == 0:
                continue
            entry = {"@input": put(self._data[klass])}
            if self._labels.get(klass) is not None:
                entry["@labels"] = put(self._labels[klass])
            if self._targets.get(klass) is not None:
                entry["@targets"] = put(self._targets[klass])
            self._dev_data[klass] = entry

        # The Pallas DMA-gather kernel is TPU-only AND opt-in: measured
        # on-chip (bench_tpu.py, v5e, 512 rows of a 60k x 784 set) XLA's
        # own gather won — 0.64 ms vs 0.84 ms — so jnp.take is the
        # default and the DMA kernel engages only on an explicit
        # ``use_pallas_gather=True`` (kept for parity with
        # ocl/fullbatch_loader.cl and for layouts where take regresses).
        # PROVISIONAL: that measurement used the pre-optimization_barrier
        # harness that BASELINE.md says flattered XLA on bandwidth-bound
        # kernels; the default follows whichever side wins the barrier'd
        # re-measurement (bench_tpu.py gather row).
        use_pallas = allow_pallas and self._use_pallas_gather is True
        if use_pallas:
            # Per-index HBM→HBM DMA kernel (parity:
            # ocl/fullbatch_loader.cl fill_minibatch_data_labels).  Big
            # arrays are packed into the kernel's tiled row layout ONCE
            # here.  The layout pads features to a multiple of 8·128, so
            # only arrays where that padding is cheap (<12.5% HBM overhead)
            # and the row is big enough to benefit from DMA are packed;
            # everything else (labels, small/awkward rows) stays on
            # jnp.take.
            from ..ops.pallas_kernels import (pack_rows, gather_rows_packed,
                                              unpack_rows)
            packed_meta = {}
            for klass, entry in self._dev_data.items():
                for key, arr in entry.items():
                    f = int(np.prod(arr.shape[1:]))
                    f_pad = -(-f // 1024) * 1024
                    if f >= 4096 and f_pad <= f * 1.125:
                        packed, f, sshape = pack_rows(arr)
                        entry[key] = packed
                        packed_meta[key] = (f, tuple(sshape))

            @jax.jit
            def gather(tree, idx):
                out = {}
                for key, a in tree.items():
                    if key in packed_meta:
                        f, sshape = packed_meta[key]
                        out[key] = unpack_rows(
                            gather_rows_packed(a, idx), f, sshape)
                    else:
                        out[key] = jnp.take(a, idx, axis=0)
                return out
        else:
            @jax.jit
            def gather(tree, idx):
                return jax.tree.map(lambda a: jnp.take(a, idx, axis=0), tree)

        self._gather = gather

    def make_batch(self, chunk: np.ndarray, klass: int):
        if not self.on_device:
            return super().make_batch(chunk, klass)
        bs = self.minibatch_size
        valid_n = len(chunk)
        if valid_n < bs:
            chunk = np.concatenate(
                [chunk, np.zeros(bs - valid_n, chunk.dtype)])
        idx = jnp.asarray(chunk, jnp.int32)
        batch = dict(self._gather(self._dev_data[klass], idx))
        mask = np.zeros(bs, np.float32)
        mask[:valid_n] = 1.0
        batch["@mask"] = jnp.asarray(mask)
        return batch
