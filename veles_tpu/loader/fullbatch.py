"""Fullbatch loader: whole dataset resident in device HBM, minibatch gather
on device.

Reference parity: veles/loader/fullbatch.py:79 — dataset uploaded to device
memory once, minibatches gathered by a fill_minibatch_data_labels kernel
(ocl/fullbatch_loader.cl) from shuffled indices; graceful host fallback on
OOM (:164-242).

TPU redesign: the dataset lives as jax Arrays in HBM; the gather runs in a
tiny jitted function — the Pallas per-index DMA kernel on TPU (barrier'd
on-chip winner, 1.42x vs jnp.take) and ``jnp.take(data, idx, axis=0)``
elsewhere — so only the *indices* cross the host→device boundary each step
(the exact analog of the reference's ship-indices-only distributed
protocol, veles/loader/base.py:631-639). On HBM-overflow the loader
transparently degrades to host-side gather (ArrayLoader behavior),
mirroring the reference's OOM fallback.
"""

from __future__ import annotations

from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..ops import use_pallas_default
from .base import ArrayLoader, TEST, TRAIN, VALID

# Packed-DMA-gather eligibility, calibrated to the on-chip measurement
# (bench_tpu.py gather row: 3,136-byte rows at 30% pad overhead won 1.42x
# vs jnp.take on v5e) — don't pack below the measured-winning envelope.
_PACK_MIN_ROW_BYTES = 3072
_PACK_MAX_PAD = 1.35


class FullBatchLoader(ArrayLoader):
    """ArrayLoader whose gather happens on device."""

    def __init__(self, *args, device=None, force_host: bool = False,
                 use_pallas_gather: Optional[bool] = None, **kw):
        super().__init__(*args, **kw)
        self._device = device
        self._force_host = force_host
        self._use_pallas_gather = use_pallas_gather
        self._dev_data: Dict[int, dict] = {}
        self._gather = None
        self.on_device = False

    def initialize(self):
        super().initialize()
        if self._force_host:
            return
        try:
            self._upload()
            self.on_device = True
            return
        except (RuntimeError, jax.errors.JaxRuntimeError) as e:
            self._dev_data.clear()
            if not self._want_pallas():
                # gather is plain jnp.take (no packed layout) — a retry
                # without packing would re-run a byte-identical upload.
                err = e
            else:
                # The packed-gather layout pads rows; if that padding is
                # what overflowed HBM, retry unpacked before giving up
                # device residency entirely.
                self.warning("device upload failed (%s); retrying without "
                             "packed gather", e)
                try:
                    self._upload(allow_pallas=False)
                    self.on_device = True
                    return
                except (RuntimeError, jax.errors.JaxRuntimeError) as e2:
                    err = e2
        # OOM fallback (reference: veles/loader/fullbatch.py:164-242).
        self.warning("device upload failed (%s); host-side gather", err)
        self._dev_data.clear()
        self.on_device = False

    def _want_pallas(self) -> bool:
        """Effective gather policy: explicit flag wins; None follows the
        shared platform default (Pallas on TPU — see comment in _upload)."""
        if self._use_pallas_gather is not None:
            return bool(self._use_pallas_gather)
        platform = (self._device.platform if self._device is not None
                    else None)
        return use_pallas_default(platform)

    def _upload(self, allow_pallas: bool = True):
        put = (lambda x: jax.device_put(x, self._device)) \
            if self._device is not None else jax.device_put
        for klass in (TEST, VALID, TRAIN):
            if self.class_lengths[klass] == 0:
                continue
            entry = {"@input": put(self._data[klass])}
            if self._labels.get(klass) is not None:
                entry["@labels"] = put(self._labels[klass])
            if self._targets.get(klass) is not None:
                entry["@targets"] = put(self._targets[klass])
            self._dev_data[klass] = entry

        # The Pallas DMA-gather kernel is the TPU default: measured on-chip
        # with the optimization_barrier'd harness (bench_tpu.py, v5e,
        # 512 rows of a 60k x 784 set) the per-index DMA kernel wins —
        # 0.63 ms vs 0.89 ms for jnp.take (1.42x; gather-only — the
        # bench row now also folds in the unpack slice, a ~1.6 MB
        # reshape that cannot flip a 0.26 ms margin).  The earlier
        # pre-barrier measurement that favored XLA (0.64 vs 0.84) let the
        # chained harness fuse away XLA's output materialization; with a
        # fair harness the winner flips, so per the reference's
        # bench-and-persist-the-winner discipline
        # (veles/backends.py:672-731) the default follows the platform
        # policy, and ``use_pallas_gather=False`` forces jnp.take.
        use_pallas = allow_pallas and self._want_pallas()
        if use_pallas:
            # Per-index HBM→HBM DMA kernel (parity:
            # ocl/fullbatch_loader.cl fill_minibatch_data_labels).  Big
            # arrays are packed into the kernel's tiled row layout ONCE
            # here.  Eligibility mirrors the measured winning envelope
            # (bench_tpu.py gather row, which times the loader's full
            # pack→gather→unpack path): the 784-feature f32 case (3.1 KB
            # rows, padded to 1024 features = 30% HBM overhead) still won
            # 1.42x, so rows of >= _PACK_MIN_ROW_BYTES with padding
            # overhead <= _PACK_MAX_PAD are packed; labels, small and
            # awkward rows stay on jnp.take.
            from ..ops.pallas_kernels import (pack_rows, gather_rows_packed,
                                              unpack_rows)
            # packed_meta is PER (class, key): the measured decision (and
            # even eligibility, via dtype) can differ between classes of
            # one dataset, and the gather jit below must exactly match
            # what its own class's arrays look like.
            packed_meta = {}
            for klass, entry in self._dev_data.items():
                for key, arr in entry.items():
                    f = int(np.prod(arr.shape[1:]))
                    f_pad = -(-f // 1024) * 1024
                    # 4-byte dtypes only: the kernel's (8, 128) block
                    # tiling and the measurements are f32/i32; narrower
                    # dtypes tile differently and were never benched.
                    if (arr.dtype.itemsize == 4
                            and f * 4 >= _PACK_MIN_ROW_BYTES
                            and f_pad <= f * _PACK_MAX_PAD
                            and self._gather_pack_wins(arr)):
                        packed, f, sshape = pack_rows(arr)
                        entry[key] = packed
                        packed_meta[(klass, key)] = (f, tuple(sshape))

            def make_gather(klass):
                @jax.jit
                def gather(tree, idx):
                    out = {}
                    for key, a in tree.items():
                        meta = packed_meta.get((klass, key))
                        if meta is not None:
                            f, sshape = meta
                            out[key] = unpack_rows(
                                gather_rows_packed(a, idx), f, sshape)
                        else:
                            out[key] = jnp.take(a, idx, axis=0)
                    return out
                return gather

            self._gather = {klass: make_gather(klass)
                            for klass in self._dev_data}
        else:
            @jax.jit
            def take_gather(tree, idx):
                return jax.tree.map(lambda a: jnp.take(a, idx, axis=0), tree)

            self._gather = {klass: take_gather
                            for klass in self._dev_data}

    def _gather_pack_wins(self, arr) -> bool:
        """Measured per-dataset-shape decision: time the full
        pack→gather→unpack path vs jnp.take on a sample slice of the
        uploaded array (per-row DMA cost is row-count independent, so a
        slice is representative) and persist the winner in the autotune
        DB. With autotune disabled the static envelope above decides
        alone (returns True). The decision uses the FULL minibatch size
        even for smaller classes so every class of one dataset shape
        agrees (the gather jits are per class, but a uniform verdict
        keeps behavior predictable)."""
        from ..config import root
        if not bool(root.common.autotune):
            return True
        from ..runtime import autotune
        f = int(np.prod(arr.shape[1:]))
        bs = self.minibatch_size
        op = f"fullbatch_gather_f{f}_{arr.dtype}_bs{bs}"
        idx = jnp.arange(bs, dtype=jnp.int32)
        names = ("packed", "take")
        cached = autotune.lookup(op, names, [idx])
        if cached is not None:  # warm start: no sample pack at all
            return cached == "packed"
        from ..ops.pallas_kernels import (pack_rows, gather_rows_packed,
                                          unpack_rows)
        n = int(min(len(arr), 4096))
        sample = arr[:n]
        packed, fp, sshape = pack_rows(sample)
        # Time with a shuffled permutation, matching the production
        # access pattern (epoch shuffles): sequential indices have a
        # locality jnp.take can exploit that a real gather never sees,
        # which would bias the persisted winner.
        idx = jnp.asarray(
            np.random.default_rng(0).permutation(n)[:bs] if n >= bs
            else np.random.default_rng(0).integers(0, n, bs),
            jnp.int32)
        winner = autotune.pick(
            op,
            {"packed": lambda i: unpack_rows(
                gather_rows_packed(packed, i), fp, sshape),
             "take": lambda i: jnp.take(sample, i, axis=0)},
            [idx], default="packed")
        return winner == "packed"

    def make_batch(self, chunk: np.ndarray, klass: int):
        if not self.on_device:
            return super().make_batch(chunk, klass)
        bs = self.minibatch_size
        valid_n = len(chunk)
        if valid_n < bs:
            chunk = np.concatenate(
                [chunk, np.zeros(bs - valid_n, chunk.dtype)])
        idx = jnp.asarray(chunk, jnp.int32)
        batch = dict(self._gather[klass](self._dev_data[klass], idx))
        mask = np.zeros(bs, np.float32)
        mask[:valid_n] = 1.0
        batch["@mask"] = jnp.asarray(mask)
        return batch

class FullBatchAugmentedLoader(FullBatchLoader):
    """Device-side random-crop + mirror augmentation over a device-resident
    uint8 image store — the TPU-native input pipeline.

    Reference analog: the host image pipeline's random crop/mirror
    (veles/loader/image.py:106) feeding the fullbatch on-device gather
    (veles/loader/fullbatch.py:79).  The reference did augmentation on the
    host because its devices were remote OpenCL contexts; on TPU the HBM
    holds the decoded uint8 store and the crop/mirror is pure slicing, so
    the whole pipeline — gather by shuffled index, per-sample dynamic-slice
    crop, conditional mirror — runs inside ONE jitted function on device.
    Per step the host ships only the index vector plus a (B, 2) crop-offset
    array and a (B,) flip mask (a few KB), not the pixels: the gather
    half of the reference's ship-indices-only discipline, extended to
    augmentation descriptors.

    Train batches get random offsets/flips drawn deterministically from the
    loader PRNG stream (reproducible across resume/shards, like
    epoch_permutation); valid/test batches get the center crop, no flip.
    The host OOM fallback reproduces identical pixels with numpy slicing.
    """

    def __init__(self, *args, crop_hw, mirror: bool = True, **kw):
        # The packed Pallas gather stores rows flattened — useless here,
        # since the crop must slice the (H, W, C) geometry before any
        # reshape; the fused take+crop below IS the device path.
        if kw.pop("use_pallas_gather", None):
            raise ValueError(
                "FullBatchAugmentedLoader fuses its own take+crop device "
                "gather; use_pallas_gather does not apply")
        super().__init__(*args, use_pallas_gather=False, **kw)
        self.crop_hw = tuple(int(c) for c in crop_hw)
        self.mirror = bool(mirror)
        self._aug = None
        self._aug_epoch = 0

    def initialize(self):
        # Validate BEFORE the (possibly GB-scale) upload: otherwise the
        # same mistake fails three different ways later (np rng low>=high
        # on train, negative center offsets on the host path, XLA
        # dynamic_slice error on device).
        ch, cw = self.crop_hw
        for klass in (TEST, VALID, TRAIN):
            if self._data.get(klass) is None:
                continue
            if self._data[klass].ndim < 3:
                raise ValueError(
                    f"class-{klass} store must be (N, H, W[, C]) images, "
                    f"got shape {self._data[klass].shape}")
            hs, ws = self._store_hw(klass)
            if ch > hs or cw > ws:
                raise ValueError(
                    f"crop_hw {self.crop_hw} exceeds class-{klass} store "
                    f"geometry {(hs, ws)}")
        super().initialize()

    def _store_hw(self, klass: int):
        return self._data[klass].shape[1:3]

    def iter_epoch(self, klass, epoch=None):
        # Stash the epoch for _draw_aug (make_batch's signature has no
        # epoch): crops must differ per epoch even after shuffle_limit
        # freezes the permutation — epoch_permutation mixes epoch into
        # its seed for the same reason (base.py). Only TRAIN draws
        # consult it, so only a TRAIN iterator may write it — an eval
        # iterator started mid-train-epoch (spec probe, mid-epoch
        # validation) must not retroactively change the train crops.
        if klass == TRAIN:
            self._aug_epoch = (self.epoch_number if epoch is None
                               else int(epoch))
        yield from super().iter_epoch(klass, epoch)

    def _draw_aug(self, n: int, klass: int, anchor: int):
        """(offsets (n,2) int32, flips (n,) bool) for one minibatch —
        deterministic in (loader seed, epoch, klass, first index),
        matching the epoch_permutation determinism contract."""
        hs, ws = self._store_hw(klass)
        ch, cw = self.crop_hw
        if klass == TRAIN:
            from .. import prng
            rng = np.random.Generator(np.random.PCG64(
                [prng.get(self.prng_name).seed, self._aug_epoch, klass,
                 anchor, 0xC407]))
            offs = np.stack([rng.integers(0, hs - ch + 1, n),
                             rng.integers(0, ws - cw + 1, n)],
                            1).astype(np.int32)
            flips = (rng.random(n) < 0.5) if self.mirror \
                else np.zeros(n, bool)
        else:
            offs = np.broadcast_to(
                np.array([(hs - ch) // 2, (ws - cw) // 2], np.int32),
                (n, 2)).copy()
            flips = np.zeros(n, bool)
        return offs, flips

    def _upload(self, allow_pallas: bool = True):
        super()._upload(allow_pallas=False)
        ch, cw = self.crop_hw

        @jax.jit
        def aug(tree, idx, offs, flips):
            out = {}
            for key, a in tree.items():
                if key == "@input":
                    imgs = jnp.take(a, idx, axis=0)

                    def crop1(img, off, flip):
                        c = jax.lax.dynamic_slice(
                            img, (off[0], off[1]) + (0,) * (img.ndim - 2),
                            (ch, cw) + img.shape[2:])
                        return jnp.where(flip, c[:, ::-1], c)

                    out[key] = jax.vmap(crop1)(imgs, offs, flips)
                else:
                    out[key] = jnp.take(a, idx, axis=0)
            return out

        self._aug = aug

    def make_batch(self, chunk: np.ndarray, klass: int):
        if not self.on_device:
            return super(FullBatchLoader, self).make_batch(chunk, klass)
        bs = self.minibatch_size
        valid_n = len(chunk)
        if valid_n < bs:
            chunk = np.concatenate(
                [chunk, np.zeros(bs - valid_n, chunk.dtype)])
        anchor = int(chunk[0]) if valid_n else 0
        offs, flips = self._draw_aug(bs, klass, anchor)
        batch = dict(self._aug(self._dev_data[klass],
                               jnp.asarray(chunk, jnp.int32),
                               jnp.asarray(offs), jnp.asarray(flips)))
        mask = np.zeros(bs, np.float32)
        mask[:valid_n] = 1.0
        batch["@mask"] = jnp.asarray(mask)
        return batch

    def fill_minibatch(self, indices, klass):
        """Host fallback: numpy slicing, pixel-identical to the device
        path (same _draw_aug descriptors)."""
        batch = super().fill_minibatch(indices, klass)
        ch, cw = self.crop_hw
        offs, flips = self._draw_aug(
            len(indices), klass, int(indices[0]) if len(indices) else 0)
        imgs = batch["@input"]
        out = np.empty(imgs.shape[:1] + (ch, cw) + imgs.shape[3:],
                       imgs.dtype)
        for i in range(len(imgs)):
            oy, ox = offs[i]
            c = imgs[i, oy:oy + ch, ox:ox + cw]
            out[i] = c[:, ::-1] if flips[i] else c
        batch["@input"] = out
        return batch
