"""Minibatch capture/replay.

Reference parity: veles/loader/saver.py — ``MinibatchesSaver`` dumped every
served minibatch to a snappy-compressed stream; ``MinibatchesLoader``
replayed them for dataset-free training (ship the minibatch file instead of
the dataset).

TPU redesign: one compressed .npz per capture with stacked batch arrays —
portable, seekable, no codec dependency."""

from __future__ import annotations

import os
from typing import Dict, List, Optional

import numpy as np

from .base import Loader, TEST, TRAIN, VALID


class MinibatchesSaver:
    """Wrap a loader; record every batch it serves."""

    def __init__(self, loader: Loader):
        self.loader = loader
        self.captured: Dict[int, List[dict]] = {TEST: [], VALID: [],
                                                TRAIN: []}

    def initialize(self):
        self.loader.initialize()

    def iter_epoch(self, klass: int, epoch=None):
        for batch in self.loader.iter_epoch(klass, epoch):
            host = {k: np.asarray(v) for k, v in batch.items()}
            self.captured[klass].append(host)
            yield batch

    def save(self, path: str) -> str:
        arrays = {}
        meta = []
        for klass, batches in self.captured.items():
            for i, b in enumerate(batches):
                for key, arr in b.items():
                    arrays[f"c{klass}_b{i}_{key.lstrip('@')}"] = arr
            meta.append(len(batches))
        arrays["__meta__"] = np.asarray(meta)
        keys = sorted({key.lstrip("@") for bs in self.captured.values()
                       for b in bs for key in b})
        arrays["__keys__"] = np.asarray(keys)
        np.savez_compressed(path, **arrays)
        return path


class MinibatchesLoader(Loader):
    """Replay captured minibatches (dataset-free training)."""

    def __init__(self, path: str, **kw):
        super().__init__(**kw)
        self.path = path
        self._batches: Dict[int, List[dict]] = {}

    def load_data(self):
        with np.load(self.path, allow_pickle=False) as z:
            meta = z["__meta__"]
            keys = [str(k) for k in z["__keys__"]]
            for klass in (TEST, VALID, TRAIN):
                n = int(meta[klass])
                batches = []
                for i in range(n):
                    b = {}
                    for key in keys:
                        zkey = f"c{klass}_b{i}_{key}"
                        if zkey in z:
                            b["@" + key] = z[zkey]
                    batches.append(b)
                self._batches[klass] = batches
                bs = (len(next(iter(batches[0].values())))
                      if batches else 0)
                self.class_lengths[klass] = sum(
                    int(b.get("@mask", np.ones(bs)).sum())
                    for b in batches)
        if self._batches[TRAIN]:
            self.minibatch_size = len(
                next(iter(self._batches[TRAIN][0].values())))

    def n_minibatches(self, klass):
        return len(self._batches.get(klass, []))

    def iter_epoch(self, klass: int, epoch=None):
        yield from self._batches.get(klass, [])

    def fill_minibatch(self, indices, klass):  # replay path bypasses this
        raise NotImplementedError("MinibatchesLoader replays whole batches")
