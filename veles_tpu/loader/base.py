"""Loader base: the minibatch-serving contract.

TPU-native re-design of the reference Loader (reference:
veles/loader/base.py:100,120 — three sample classes test/valid/train :72-80,
per-epoch shuffling :711-724, epoch/last-minibatch flags :862-878, label
mapping + distribution analysis :925-1018, normalization analysis pass
:755-803, failed/pending minibatch tracking for slave dropout :679-687,
master-slave protocol shipping only indices :631-663).

Key redesigns for SPMD/XLA:

* **Static shapes.** XLA compiles per shape; the reference's variable last
  minibatch becomes a fixed-size batch padded with a ``@mask`` array the
  evaluators consume — metrics stay exact while every step hits the same
  compiled program.
* **Deterministic sharded epochs.** Instead of a master shipping indices to
  slaves (and requeueing failed minibatches), each epoch is a deterministic
  permutation derived from (seed, epoch); under data parallelism each host
  slices its own shard of the permutation — same accounting, no protocol
  (SURVEY.md §7 "hard parts": loader statefulness vs SPMD).
* **Checkpointable.** ``state()``/``set_state()`` capture epoch, position and
  PRNG state so resume continues the exact data order (reference restored
  loader counters via pickle).
"""

from __future__ import annotations

from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from .. import prng
from ..logger import Logger

# Reference class order (veles/loader/base.py:72-80).
TEST, VALID, TRAIN = 0, 1, 2
CLASS_NAMES = ("test", "validation", "train")


class LoaderError(Exception):
    pass


class Loader(Logger):
    """Abstract minibatch server.

    Subclasses implement :meth:`load_data` (fill ``class_lengths``) and
    :meth:`fill_minibatch` (produce arrays for given global sample indices).
    """

    def __init__(self, minibatch_size: int = 100, *,
                 shuffle_limit: float = np.inf,
                 prng_name: str = "loader",
                 shard_index: int = 0, shard_count: int = 1,
                 train_ratio: float = 1.0, subset_seed: int = 0):
        self.minibatch_size = int(minibatch_size)
        self.class_lengths: List[int] = [0, 0, 0]
        self.shuffle_limit = shuffle_limit  # epochs after which shuffling stops
        self.epoch_number = 0
        self.prng_name = prng_name
        self.shard_index = int(shard_index)
        self.shard_count = int(shard_count)
        # train_ratio < 1 trains on a fixed random subset (ensemble bagging,
        # reference: veles/ensemble train_ratio semantics).
        self.train_ratio = float(train_ratio)
        self.subset_seed = int(subset_seed)
        self.normalizer = None
        self._loaded = False

    # -- subclass contract -------------------------------------------------
    def load_data(self) -> None:
        """Populate class_lengths (and any dataset storage)."""
        raise NotImplementedError

    def fill_minibatch(self, indices: np.ndarray, klass: int
                       ) -> Dict[str, np.ndarray]:
        """Return batch arrays for the given within-class sample indices.
        Keys are workflow input names ("@input", "@labels", "@targets")."""
        raise NotImplementedError

    # -- lifecycle ---------------------------------------------------------
    def initialize(self) -> None:
        if self._loaded:
            return
        self.load_data()
        self._loaded = True
        if sum(self.class_lengths) == 0:
            raise LoaderError("loader has no samples")
        self.info("dataset: test=%d valid=%d train=%d, minibatch=%d",
                  *self.class_lengths, self.minibatch_size)

    @property
    def total_samples(self) -> int:
        return sum(self.class_lengths)

    def class_offset(self, klass: int) -> int:
        return sum(self.class_lengths[:klass])

    # -- epoch iteration ---------------------------------------------------
    def _train_indices(self, klass: int) -> np.ndarray:
        """Class sample indices, restricted to the bagging subset for
        train when train_ratio < 1."""
        n = self.class_lengths[klass]
        if klass != TRAIN or self.train_ratio >= 1.0:
            return np.arange(n)
        keep = max(1, int(round(n * self.train_ratio)))
        rng = np.random.Generator(
            np.random.PCG64([self.subset_seed, 0xBA66]))
        return np.sort(rng.choice(n, size=keep, replace=False))

    def epoch_permutation(self, klass: int,
                          epoch: Optional[int] = None) -> np.ndarray:
        """Deterministic permutation for (class, epoch). Train shuffles per
        epoch (until shuffle_limit); valid/test are served in order
        (reference: veles/loader/base.py:711-724)."""
        base = self._train_indices(klass)
        if epoch is None:
            epoch = self.epoch_number
        if klass != TRAIN or epoch >= self.shuffle_limit:
            return base
        seed_stream = prng.get(self.prng_name)
        rng = np.random.Generator(
            np.random.PCG64([seed_stream.seed, epoch, klass]))
        return rng.permutation(base)

    def n_minibatches(self, klass: int) -> int:
        n = len(self._train_indices(klass))
        if self.shard_count > 1:
            n = -(-n // self.shard_count)
        return -(-n // self.minibatch_size) if n else 0

    def iter_epoch(self, klass: int, epoch: Optional[int] = None
                   ) -> Iterator[Dict[str, np.ndarray]]:
        """Yield fixed-size padded batches with '@mask'. Under sharding, this
        host sees a strided slice of the permutation (reference analog: the
        master shipped index subsets to each slave). EVERY shard yields the
        same number of batches (padding fully-empty ones at the tail if its
        slice runs short) — all hosts must drive the same count of compiled
        collective steps or multi-host SPMD hangs."""
        perm = self.epoch_permutation(klass, epoch)
        n_batches = self.n_minibatches(klass)
        if self.shard_count > 1:
            perm = perm[self.shard_index::self.shard_count]
        bs = self.minibatch_size
        for i in range(n_batches):
            chunk = perm[i * bs:(i + 1) * bs]
            if len(chunk) == 0:  # shard exhausted: fully-masked batch
                chunk = np.zeros(0, np.int64)
            yield self._fetch_batch(chunk, klass, i)

    def _fetch_batch(self, chunk: np.ndarray, klass: int,
                     batch_index: int) -> Dict[str, np.ndarray]:
        """``make_batch`` with bounded transient-read retry — the rebuild's
        analog of the reference master re-serving a failed minibatch
        (veles/loader/base.py:679-687).  ``OSError`` from the underlying
        read (flaky NFS/HDFS/object store) retries up to
        ``root.common.loader.retries`` times with exponential backoff;
        exhaustion re-raises as :class:`LoaderError` naming the failing
        batch index so the epoch position is diagnosable."""
        import time as _time
        from ..config import root
        retries = int(root.common.loader.get("retries", 2))
        backoff = float(root.common.loader.get("retry_backoff_s", 0.05))
        attempt = 0
        while True:
            try:
                self._maybe_inject_fault(batch_index)
                return self.make_batch(chunk, klass)
            except OSError as e:
                if attempt >= retries:
                    raise LoaderError(
                        f"minibatch {batch_index} (class "
                        f"{CLASS_NAMES[klass]}) failed after "
                        f"{attempt + 1} attempts: {e}") from e
                delay = backoff * (2 ** attempt)
                self.warning(
                    "transient read error on minibatch %d (attempt "
                    "%d/%d): %s — retrying in %.2fs", batch_index,
                    attempt + 1, retries + 1, e, delay)
                _time.sleep(delay)
                attempt += 1

    def _maybe_inject_fault(self, batch_index: int) -> None:
        """Fault-harness hook (runtime/faults.py): an armed
        ``loader_ioerror_at_batch`` raises OSError on the FIRST fetch of
        that index (so the bounded retry above recovers);
        ``slow_batch_ms`` stalls every fetch."""
        from ..runtime import faults
        if not faults.enabled():
            return
        plan = faults.get_plan()
        if plan.slow_batch_ms > 0:
            import time as _time
            _time.sleep(plan.slow_batch_ms / 1e3)
        if (batch_index in plan.loader_ioerror_at_batch
                and faults.fire_once("loader_ioerror", batch_index)):
            raise OSError(
                f"injected loader IOError at batch {batch_index}")

    def make_batch(self, chunk: np.ndarray, klass: int
                   ) -> Dict[str, np.ndarray]:
        bs = self.minibatch_size
        valid_n = len(chunk)
        if valid_n < bs:  # pad by repeating index 0; mask zeroes them out
            pad = np.zeros(bs - valid_n, dtype=chunk.dtype)
            chunk = np.concatenate([chunk, pad])
        batch = self.fill_minibatch(chunk, klass)
        mask = np.zeros(bs, np.float32)
        mask[:valid_n] = 1.0
        if "@mask" in batch:
            # loader-supplied mask (e.g. per-position loss weighting for
            # sequence training): AND it with the padding mask so padded
            # tail samples stay excluded. Host-path contract: the
            # on-device FullBatchLoader gather returns only uploaded
            # keys, so device-path loaders layer custom masks in a
            # make_batch override instead (see models/lm.py).
            m = np.asarray(batch["@mask"], np.float32)
            batch["@mask"] = m * mask.reshape((bs,) + (1,) * (m.ndim - 1))
        else:
            batch["@mask"] = mask
        return batch

    def next_epoch(self) -> None:
        self.epoch_number += 1

    # -- label statistics (reference :925-1018) -----------------------------
    def analyze_label_distribution(self, labels_by_class: Dict[int, Sequence]
                                   ) -> Dict[str, dict]:
        """Per-class label histogram + a chi-square-style balance report
        between train and validation label distributions."""
        report = {}
        hists = {}
        for klass, labels in labels_by_class.items():
            vals, counts = np.unique(np.asarray(labels), return_counts=True)
            hists[klass] = dict(zip(vals.tolist(), counts.tolist()))
            report[CLASS_NAMES[klass]] = hists[klass]
        if TRAIN in hists and VALID in hists and hists[VALID]:
            keys = sorted(set(hists[TRAIN]) | set(hists[VALID]))
            tr = np.array([hists[TRAIN].get(k, 0) for k in keys], np.float64)
            va = np.array([hists[VALID].get(k, 0) for k in keys], np.float64)
            tr_p = tr / max(tr.sum(), 1)
            expected = tr_p * va.sum()
            with np.errstate(divide="ignore", invalid="ignore"):
                chi2 = float(np.nansum(
                    np.where(expected > 0,
                             np.square(va - expected) / expected, 0.0)))
            report["train_valid_chi2"] = chi2
        return report

    # -- checkpointable state (reference: pickle of loader counters) --------
    def state(self) -> dict:
        st = {"epoch_number": self.epoch_number,
              "minibatch_size": self.minibatch_size,
              "shard_index": self.shard_index,
              "shard_count": self.shard_count,
              "train_ratio": self.train_ratio,
              "subset_seed": self.subset_seed}
        if self.normalizer is not None:
            st["normalizer"] = {
                "mapping": type(self.normalizer).MAPPING,
                "state": {k: (v.tolist() if isinstance(v, np.ndarray) else v)
                          for k, v in self.normalizer.state().items()},
            }
        return st

    def set_state(self, st: dict) -> None:
        self.epoch_number = int(st["epoch_number"])
        self.minibatch_size = int(st["minibatch_size"])
        # shard_index/shard_count are TOPOLOGY, not training state: a
        # multi-host restore reads host-0's snapshot on every host, and
        # adopting its shard identity would make all hosts train shard 0
        # (silent data loss). They stay in state() for inspection only.
        self.train_ratio = float(st.get("train_ratio", 1.0))
        self.subset_seed = int(st.get("subset_seed", 0))
        norm = st.get("normalizer")
        if norm:
            from ..normalization import NormalizerRegistry
            if (self.normalizer is None
                    or type(self.normalizer).MAPPING != norm["mapping"]):
                self.normalizer = NormalizerRegistry.create(norm["mapping"])
            self.normalizer.set_state({
                k: (np.asarray(v, np.float32) if isinstance(v, list) else v)
                for k, v in norm["state"].items()})


class ArrayLoader(Loader):
    """Loader over in-memory numpy arrays (the workhorse for tests and
    synthetic benchmarks; reference analog: FullBatchLoader's host half,
    veles/loader/fullbatch.py:79).

    ``data[klass]`` -> (N, ...) inputs; ``labels[klass]`` -> (N,) int labels
    or None; ``targets[klass]`` -> regression targets or None.
    """

    def __init__(self, data: Dict[int, np.ndarray],
                 labels: Optional[Dict[int, np.ndarray]] = None,
                 targets: Optional[Dict[int, np.ndarray]] = None,
                 normalizer=None, **kw):
        super().__init__(**kw)
        self._data = data
        self._labels = labels or {}
        self._targets = targets or {}
        self.normalizer = normalizer

    def load_data(self):
        for klass in (TEST, VALID, TRAIN):
            arr = self._data.get(klass)
            self.class_lengths[klass] = 0 if arr is None else len(arr)
        if self.normalizer is not None:
            for klass in (TRAIN,):  # stats from train only
                if self._data.get(klass) is not None:
                    self.normalizer.analyze(self._data[klass])
            for klass in (TEST, VALID, TRAIN):
                if self._data.get(klass) is not None:
                    self._data[klass] = self.normalizer.normalize(
                        self._data[klass])

    def fill_minibatch(self, indices, klass):
        batch = {"@input": self._data[klass][indices]}
        if klass in self._labels and self._labels[klass] is not None:
            batch["@labels"] = self._labels[klass][indices]
        if klass in self._targets and self._targets[klass] is not None:
            batch["@targets"] = self._targets[klass][indices]
        return batch
