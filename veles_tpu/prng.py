"""Seeded, named PRNG streams.

TPU-native re-design of the reference PRNG registry (reference:
veles/prng/random_generator.py:289 ``prng.get(index)`` — numbered global
generators seeded from CLI ``--random-seed index:seed`` specs,
veles/__main__.py:483-537) and of the per-unit reproducibility contract
(reference: veles/units.py:859-885 ``_ensure_reproducible_rg``).

JAX PRNG keys are explicit and splittable (threefry), so reproducibility is
structural rather than promised: every stream is a deterministic function of
(master seed, stream name, fold count). Host-side randomness (loader shuffles)
uses numpy Generators derived from the same seeds so checkpoints can capture
loader state exactly.
"""

from __future__ import annotations

import threading
from typing import Dict

import jax
import numpy as np

from .config import root


class RandomStream:
    """One named stream: a JAX key chain plus a numpy Generator.

    ``next_key()`` advances the on-device key chain; ``numpy`` is the host-side
    generator (used by loaders for epoch permutations). Both are restorable:
    state() / set_state() round-trip through checkpoints (reference parity:
    loader counters restored via pickle, veles/loader/base.py:617-618).
    """

    def __init__(self, name: str, seed: int):
        self.name = name
        self.seed = int(seed)
        self._count = 0
        self.numpy = np.random.Generator(np.random.PCG64(self.seed))
        self._lock = threading.Lock()

    @property
    def key(self) -> jax.Array:
        """Current JAX key (does not advance)."""
        k = jax.random.key(self.seed)
        if self._count:
            k = jax.random.fold_in(k, self._count)
        return k

    def next_key(self) -> jax.Array:
        """Advance and return a fresh JAX key."""
        with self._lock:
            self._count += 1
            return jax.random.fold_in(jax.random.key(self.seed), self._count)

    def next_keys(self, n: int):
        return jax.random.split(self.next_key(), n)

    def randint(self, low, high=None, size=None):
        return self.numpy.integers(low, high, size)

    def permutation(self, n: int) -> np.ndarray:
        return self.numpy.permutation(n)

    def state(self) -> dict:
        return {
            "seed": self.seed,
            "count": self._count,
            "numpy": self.numpy.bit_generator.state,
        }

    def set_state(self, st: dict) -> None:
        self.seed = int(st["seed"])
        self._count = int(st["count"])
        self.numpy = np.random.Generator(np.random.PCG64(0))
        self.numpy.bit_generator.state = st["numpy"]


class _Registry:
    def __init__(self):
        self._streams: Dict[str, RandomStream] = {}
        self._lock = threading.Lock()

    def get(self, name: str = "default") -> RandomStream:
        """Fetch-or-create the named stream (reference: ``prng.get(index)``,
        veles/prng/random_generator.py:289; names replace indices)."""
        with self._lock:
            if name not in self._streams:
                master = int(root.common.value("random_seed", 42))
                # Derive a per-stream seed deterministically from the name.
                sub = np.random.SeedSequence(
                    [master, *[ord(c) for c in name]]).generate_state(1)[0]
                self._streams[name] = RandomStream(name, int(sub))
            return self._streams[name]

    def seed(self, name: str, seed: int) -> RandomStream:
        """Explicitly (re)seed a stream (CLI ``--random-seed`` parity,
        veles/__main__.py:483-537)."""
        with self._lock:
            self._streams[name] = RandomStream(name, seed)
            return self._streams[name]

    def state(self) -> dict:
        with self._lock:
            return {k: v.state() for k, v in self._streams.items()}

    def set_state(self, st: dict) -> None:
        with self._lock:
            for k, s in st.items():
                stream = self._streams.get(k)
                if stream is None:
                    stream = self._streams[k] = RandomStream(k, s["seed"])
                stream.set_state(s)

    def reset(self) -> None:
        with self._lock:
            self._streams.clear()


streams = _Registry()
get = streams.get
seed = streams.seed
