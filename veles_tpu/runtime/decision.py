"""Decision: epoch-level training control.

Reference parity: the Znicz Decision unit (reference: docs
manualrst_veles_units.rst; SURVEY.md §2.10) tracked train/valid errors per
epoch, decided when to stop, and owned the "best snapshot" notion, including
"rollback to best snapshot on failure + lr change"
(manualrst_veles_algorithms.rst:164 item 11).

In the rebuild this is host-side loop control (the one place data-dependent
control flow belongs — outside jit)."""

from __future__ import annotations

import math
from typing import Dict, Optional

from ..logger import Logger


class Decision(Logger):
    """Tracks epoch metrics, detects improvement, decides stop/rollback.

    * ``max_epochs`` — hard epoch budget (None = unlimited).
    * ``fail_iterations`` — stop after this many epochs without validation
      improvement (reference Decision semantic).
    * ``metric`` — key into the aggregated epoch metrics; lower is better
      (error %, loss, rmse).
    * ``rollback_after`` — if set, request a rollback to the best state after
      this many non-improving epochs, multiplying lr by ``rollback_lr_scale``
      (reference item 11).
    """

    def __init__(self, max_epochs: Optional[int] = None,
                 fail_iterations: int = 50, metric: str = "error_pct",
                 rollback_after: Optional[int] = None,
                 rollback_lr_scale: float = 0.5):
        self.max_epochs = max_epochs
        self.fail_iterations = fail_iterations
        self.metric = metric
        self.rollback_after = rollback_after
        self.rollback_lr_scale = rollback_lr_scale

        self.best_value = math.inf
        self.best_epoch = -1
        self.epochs_since_improvement = 0
        self.complete = False
        self.improved = False
        self.want_rollback = False
        self.lr_multiplier = 1.0
        self._gauge_key = metric
        self.history: list = []

    def on_epoch(self, epoch: int, train_metrics: Dict[str, float],
                 valid_metrics: Dict[str, float]) -> bool:
        """Feed epoch results; returns True when training should stop."""
        gauge = valid_metrics if valid_metrics else train_metrics
        # fall back classification -> regression -> raw loss, and report
        # the key actually used (an MSE workflow's gauge is its RMSE, not
        # a metric labeled "error_pct")
        used = self.metric
        value = gauge.get(used)
        if value is None:
            for used in ("rmse", "loss"):
                if used in gauge:
                    value = gauge[used]
                    break
            else:
                used, value = "loss", math.inf
        self._gauge_key = used
        self.history.append(
            {"epoch": epoch, "train": dict(train_metrics),
             "valid": dict(valid_metrics), "value": value,
             "metric": used})

        self.improved = value < self.best_value
        self.want_rollback = False
        if self.improved:
            self.best_value = value
            self.best_epoch = epoch
            self.epochs_since_improvement = 0
        else:
            self.epochs_since_improvement += 1
            if (self.rollback_after is not None
                    and self.epochs_since_improvement > 0
                    and self.epochs_since_improvement
                    % self.rollback_after == 0):
                self.want_rollback = True
                self.lr_multiplier *= self.rollback_lr_scale
                self.info("rollback requested at epoch %d (lr ×%g)",
                          epoch, self.lr_multiplier)

        self.info("epoch %d: %s=%.4f (best %.4f @ %d)%s", epoch,
                  self._gauge_key, value, self.best_value, self.best_epoch,
                  " *" if self.improved else "")

        if self.max_epochs is not None and epoch + 1 >= self.max_epochs:
            self.complete = True
        if self.epochs_since_improvement >= self.fail_iterations:
            self.info("no improvement for %d epochs — stopping",
                      self.epochs_since_improvement)
            self.complete = True
        return self.complete

    #: config knobs belong to the CURRENT run's config — restoring them
    #: from a snapshot would silently pin a resumed run to the ORIGINAL
    #: run's settings (observed: a curriculum fine-tune with
    #: max_epochs=330 stopping at the phase-1 budget of 170). Progress
    #: fields (best_value, epochs_since_improvement, lr_multiplier...)
    #: DO restore.
    _CONFIG_KEYS = ("max_epochs", "fail_iterations", "metric",
                    "rollback_after", "rollback_lr_scale")

    def state(self) -> dict:
        return {k: v for k, v in vars(self).items() if not k.startswith("_")}

    def set_state(self, st: dict) -> None:
        # ``complete`` is derived from progress vs budget: keep it only
        # when the budget is unchanged (plain resume of a finished run
        # exits immediately); under a NEW budget it must be recomputed,
        # i.e. training continues.
        same_budget = (st.get("max_epochs") == self.max_epochs and
                       st.get("fail_iterations") == self.fail_iterations)
        for k, v in st.items():
            if k in self._CONFIG_KEYS or (k == "complete"
                                          and not same_budget):
                continue
            setattr(self, k, v)
        if "metric" in st and st["metric"] != self.metric:
            # best_value is in the SAVED metric's units; comparing the
            # current metric against it would freeze/poison improvement
            # tracking — start the gauge fresh under the new metric
            self.best_value = float("inf")
            self.best_epoch = -1
            self.epochs_since_improvement = 0
