"""Compiled-artifact runner: serve a sealed StableHLO export with zero
model Python.

``export_compiled()`` (export/compiled.py) seals a workflow's inference
step family — the decode engine's fixed program set plus the batched
forward — into a directory of serialized StableHLO programs, a
manifest, and a weights blob.  :class:`ArtifactRunner` is the other
half: it loads that directory and serves ``generate()``-compatible
decode through the SAME continuous-batching scheduler as the live
:class:`~veles_tpu.runtime.engine.DecodeEngine` (it *is* one — the
subclass only replaces the three program hooks), except that no model
code is ever traced: every program is ``jax.export.deserialize``d and
AOT-compiled at load, and the StepCache counters stay flat from the
first request to the last, across hot swaps included (the
tests/test_artifact.py contract).

Integrity and failure semantics mirror snapshots: every blob's sha256
is verified against the manifest before anything runs
(:class:`~veles_tpu.runtime.snapshotter.SnapshotCorruptError` on
mismatch), a serialized program from a newer ``jax.export`` calling
convention fails with :class:`ArtifactVersionError` naming both
versions (re-export, don't guess), and a foreign platform fails before
the first request rather than mid-decode.

The control plane speaks ``artifact://`` too: ``ModelRegistry`` entries
carry ``kind="artifact"``, ``DeployController.reload`` hot-swaps a live
engine onto an artifact's weights, and ``veles-tpu --serve --artifact
DIR`` boots this runner without the model's Python config at all.
"""

from __future__ import annotations

import json
import os
from typing import Dict, Optional

import jax
import jax.export  # noqa: F401 — not auto-imported by `import jax`
import jax.numpy as jnp
import numpy as np

from ..export.compiled import FORMAT, FORMAT_VERSION, MANIFEST
from .engine import DecodeEngine
from .snapshotter import SnapshotCorruptError, _unflatten, sha256_files
from .step_cache import StepCache


class ArtifactError(RuntimeError):
    """The artifact is structurally unusable here (missing manifest,
    missing program, foreign platform) — distinct from integrity
    corruption (:class:`SnapshotCorruptError`: re-fetch the bytes) and
    from version skew (:class:`ArtifactVersionError`: re-export)."""


class ArtifactVersionError(ArtifactError):
    """The serialized programs use a ``jax.export`` calling convention
    this process cannot replay — the fix is re-exporting the artifact
    with a matching jax, not retrying the load."""


def is_artifact_dir(path: str) -> bool:
    """Directory holds a compiled-artifact manifest — the control
    plane's dispatch test (before the package's contents.json test)."""
    return os.path.isfile(os.path.join(str(path), MANIFEST))


def read_manifest(art_dir: str) -> dict:
    """Parse ``artifact.json`` (no blob verification — that is
    :func:`verify_artifact`'s job, and the runner always runs both)."""
    path = os.path.join(art_dir, MANIFEST)
    try:
        with open(path) as f:
            man = json.load(f)
    except FileNotFoundError:
        raise ArtifactError(
            f"{art_dir!r} is not a compiled artifact (no {MANIFEST}; "
            "produce one with export_compiled / veles-tpu --export "
            "--compiled)") from None
    except (OSError, json.JSONDecodeError, UnicodeDecodeError) as e:
        raise SnapshotCorruptError(
            f"{path}: unparseable artifact manifest ({e})") from e
    if not isinstance(man, dict) or man.get("format") != FORMAT:
        raise ArtifactError(f"{path}: not a compiled-artifact manifest")
    try:
        ver = int(man.get("format_version", 1))
    except (TypeError, ValueError) as e:
        raise SnapshotCorruptError(
            f"{path}: artifact manifest is damaged (format_version "
            f"{man.get('format_version')!r}) — re-export") from e
    if int(ver) > FORMAT_VERSION:
        raise ArtifactVersionError(
            f"{path}: artifact format version {ver} is newer than this "
            f"veles-tpu understands ({FORMAT_VERSION}) — upgrade, or "
            "re-export with a matching version")
    # structural keys the consumers index unconditionally: a
    # parseable-but-damaged manifest must raise the corruption error
    # here, not a bare KeyError from the first man["tensors"] /
    # man["slots"] / input_spec["shape"]
    progs = man.get("programs") or {}
    entries = []
    ok = isinstance(man.get("tensors"), str) and isinstance(progs, dict)
    if ok:
        for key, p in progs.items():
            entries.extend(p.values() if key == "prefill"
                           and isinstance(p, dict) else [p])
        ok = all(isinstance(q, dict) and isinstance(q.get("file"), str)
                 for q in entries)
    if ok and isinstance(progs.get("prefill"), dict):
        # bucket keys index the program inventory as ints
        ok = all(str(k).isdigit() for k in progs["prefill"])
    if ok and "decode" in progs:  # the ArtifactRunner geometry keys
        ok = all(isinstance(man.get(k), int)
                 for k in ("slots", "l_max", "bucket_min"))
        if ok and man.get("paged"):  # v2 paged layout: pool geometry
            ok = all(isinstance(man.get(k), int)
                     for k in ("page_size", "pages"))
    if ok and "forward" in progs:  # load_forward's input signature
        ispec = man.get("input_spec")
        ok = isinstance(ispec, dict) and isinstance(
            ispec.get("shape"), list) and "dtype" in ispec
    if not ok:
        raise SnapshotCorruptError(
            f"{path}: artifact manifest is damaged (tensors, program "
            "file, geometry, or input_spec entries missing or "
            "malformed) — re-export")
    return man


def _verify_blob(path: str, want: Optional[str]) -> None:
    """One blob against its manifest sha256 (no-op without one) —
    SnapshotCorruptError on unreadable or mismatching bytes."""
    if not want:
        return
    try:
        got = sha256_files([path])
    except OSError as e:
        raise SnapshotCorruptError(
            f"{path}: artifact blob unreadable ({e})") from e
    if got != want:
        raise SnapshotCorruptError(
            f"{path}: artifact checksum mismatch (manifest "
            f"{want[:12]}…, blob {got[:12]}…)")


def verify_artifact(art_dir: str, man: dict) -> None:
    """Check every blob the manifest names against its recorded sha256
    — the snapshot checksum discipline applied to the artifact: torn
    or bit-flipped bytes raise :class:`SnapshotCorruptError` BEFORE a
    single program deserializes."""
    blobs = [(man["tensors"], man.get("tensors_sha256"))]
    progs = man.get("programs", {})
    for key, p in progs.items():
        if key == "prefill":
            blobs.extend((q["file"], q.get("sha256"))
                         for q in p.values())
        else:
            blobs.append((p["file"], p.get("sha256")))
    for rel, want in blobs:
        _verify_blob(os.path.join(art_dir, rel), want)


def load_artifact_weights(art_dir: str, man: Optional[dict] = None,
                          *, verify: bool = True) -> Dict[str, dict]:
    """The weights blob as host numpy trees ``{"params": ..,
    "state": ..}`` — what the deploy control plane hot-swaps onto a
    LIVE engine from an ``artifact://`` source (the programs stay the
    live engine's own; same-architecture weights are all a swap moves).
    """
    man = man if man is not None else read_manifest(art_dir)
    npz_path = os.path.join(art_dir, man["tensors"])
    if verify:
        _verify_blob(npz_path, man.get("tensors_sha256"))
    try:
        with np.load(npz_path, allow_pickle=False) as z:
            flat = {k: z[k] for k in z.files}
    except (OSError, ValueError, EOFError) as e:
        raise SnapshotCorruptError(
            f"{npz_path}: artifact tensors unreadable ({e})") from e
    tree = _unflatten(flat)
    return {"params": tree.get("params") or {},
            "state": tree.get("state") or {}}


def _check_platform(info: dict, what: str) -> None:
    """The serving platform must be one the program was lowered for —
    fail at LOAD, not mid-request (the documented semantics)."""
    platform = jax.default_backend()
    declared = info.get("platforms") or []
    # default_backend says 'gpu' where jax.export canonicalizes the
    # lowering platform to 'cuda'/'rocm' — compare the whole alias set,
    # or every GPU-exported artifact would be refused on GPU
    aliases = {platform} | ({"cuda", "rocm"} if platform == "gpu"
                            else set())
    if declared and not aliases & set(declared):
        raise ArtifactError(
            f"artifact program {what!r} was exported for platform(s) "
            f"{declared}, this process runs {platform!r} — re-export "
            "on (or for) the serving platform")


def _check_version(man: dict, what: str, info: dict) -> None:
    ver = info.get("calling_convention_version")
    if ver is None:
        return
    lo = jax.export.minimum_supported_calling_convention_version
    hi = jax.export.maximum_supported_calling_convention_version
    if not lo <= int(ver) <= hi:
        raise ArtifactVersionError(
            f"artifact program {what!r} was serialized with jax.export "
            f"calling convention {ver} (exporter jax "
            f"{man.get('jax_version')}), but this jax {jax.__version__} "
            f"supports [{lo}, {hi}] — re-export the artifact with a "
            "matching jax version")


def _deserialize(art_dir: str, man: dict, what: str, info: dict):
    _check_platform(info, what)
    _check_version(man, what, info)
    path = os.path.join(art_dir, info["file"])
    with open(path, "rb") as f:
        data = f.read()
    try:
        return jax.export.deserialize(bytearray(data))
    except Exception as e:  # noqa: BLE001 — flatbuffer/version errors
        # checksums already passed, so bad bytes mean producer/consumer
        # skew, not corruption in transit
        raise ArtifactVersionError(
            f"artifact program {what!r} failed to deserialize "
            f"({type(e).__name__}: {e}); it was exported by jax "
            f"{man.get('jax_version')} — re-export with a jax this "
            f"process ({jax.__version__}) can replay") from e


def _zeros_from_rows(rows) -> dict:
    """Rebuild a zeroed pytree from manifest ``[{path, shape, dtype}]``
    rows (the cache skeleton — the runner owns the slot state without
    ever seeing the model's cache-construction code).  Structural
    marker rows (``__seq__`` / ``__emptydict__``) replay their recorded
    values — _unflatten reads them to rebuild sequences and empty
    dicts."""
    flat = {}
    for r in rows:
        if "structure" in r:
            flat[r["path"]] = np.asarray(r["structure"],
                                         np.dtype(r["dtype"]))
        else:
            flat[r["path"]] = jnp.zeros(tuple(r["shape"]),
                                        jnp.dtype(r["dtype"]))
    if set(flat) <= {"/__emptydict__"}:
        return {}  # cache-free chain: _unflatten can't see a top-level
    return _unflatten(flat)  # empty dict behind the marker's prefix


def load_forward(art_dir: str):
    """Load ONLY the batched forward program of an artifact (the leg
    every export carries, decodable chain or not): returns
    ``(predict_fn, wstate, manifest)`` where ``predict_fn(wstate,
    batch)`` follows the ``make_predict_step`` contract — what
    ``--serve --artifact`` boots for a forward-only model."""
    art_dir = str(art_dir)
    man = read_manifest(art_dir)
    verify_artifact(art_dir, man)
    progs = man.get("programs", {})
    if "forward" not in progs:
        raise ArtifactError(
            f"artifact {art_dir!r} holds no forward program (exported "
            "without an input spec?)")
    exp = _deserialize(art_dir, man, "forward", progs["forward"])
    parts = load_artifact_weights(art_dir, man, verify=False)
    wstate = {"params": jax.device_put(parts["params"]),
              "state": jax.device_put(parts["state"])}
    # AOT-compile NOW (jax.jit alone is lazy): a program this process
    # can't lower must fail here, not inside the first /predict
    sds = lambda t: jax.tree.map(  # noqa: E731
        lambda a: jax.ShapeDtypeStruct(np.shape(a), a.dtype), t)
    ispec = man.get("input_spec") or {}
    fn = jax.jit(exp.call).lower(
        sds(wstate["params"]), sds(wstate["state"]),
        jax.ShapeDtypeStruct(tuple(int(s) for s in ispec["shape"]),
                             jnp.dtype(ispec["dtype"]))).compile()

    def predict(wstate, batch):
        return fn(wstate["params"], wstate.get("state") or {},
                  batch["@input"])

    return predict, wstate, man


class ArtifactRunner(DecodeEngine):
    """A :class:`DecodeEngine` whose programs come from a sealed
    artifact instead of traced model code.

    Same public contract — ``submit`` / ``generate`` / ``swap_params``
    / ``drain`` / ``stats`` and the REST + deploy integrations — with
    the three program hooks replaced: caches rebuild from manifest
    avals, the head width is the manifest's ``vocab``, and
    prefill/decode are ``jax.export.deserialize``d programs AOT-compiled
    at load through the StepCache (every compile happens HERE; the
    counters must not move afterwards — per request, per swap).
    """

    def __init__(self, art_dir: str, *,
                 window_ms: Optional[float] = None,
                 queue_depth: Optional[int] = None,
                 deadline_s: Optional[float] = None, status=None,
                 spec: Optional[bool] = None,
                 megastep: Optional[int] = None):
        self.art_dir = str(art_dir)
        man = read_manifest(self.art_dir)
        verify_artifact(self.art_dir, man)
        progs = man.get("programs", {})
        if "decode" not in progs:
            why = man.get("decode_unsupported", "forward-only export")
            raise ArtifactError(
                f"artifact {art_dir!r} holds no decode program ({why}); "
                "ArtifactRunner serves decode — a forward-only "
                "artifact loads through load_forward() instead")
        # speculative decode is served iff the verify program is part
        # of the SEALED inventory (manifest spec_decode + the program
        # blob).  Default: serve what the artifact seals; an explicit
        # spec=True against an unsealed artifact is refused loudly —
        # the runner has no model code to trace a verify program from.
        spec_meta = man.get("spec_decode") or None
        if spec_meta is not None and (
                not isinstance(spec_meta, dict)
                or not isinstance(spec_meta.get("k"), int)
                or "verify" not in progs):
            raise SnapshotCorruptError(
                f"{art_dir}: artifact manifest spec_decode entry is "
                "damaged (no static k, or no sealed verify program) — "
                "re-export")
        want_spec = bool(spec_meta) if spec is None else bool(spec)
        if want_spec and spec_meta is None:
            raise ArtifactError(
                f"artifact {art_dir!r} seals no speculative verify "
                "program (spec_decode absent from the manifest); "
                "re-export with export_compiled(..., spec=True) — the "
                "runner cannot trace one from sealed programs")
        # megastep decode is served iff the fused program is part of
        # the SEALED inventory (manifest megastep + the program blob);
        # artifacts without it — every v1/v2, and v3 exports at
        # megastep=1 — load unchanged and serve plain per-token decode.
        # An explicit megastep > 1 must match the sealed static N: the
        # runner has no model code to trace another fused program from.
        mega_meta = man.get("megastep") or None
        if mega_meta is not None and (
                not isinstance(mega_meta, dict)
                or not isinstance(mega_meta.get("n"), int)
                or mega_meta["n"] < 2
                or "megastep" not in progs):
            raise SnapshotCorruptError(
                f"{art_dir}: artifact manifest megastep entry is "
                "damaged (no static n >= 2, or no sealed megastep "
                "program) — re-export")
        sealed_n = int(mega_meta["n"]) if mega_meta else 1
        want_mega = sealed_n if megastep is None else int(megastep)
        if want_mega > 1 and want_mega != sealed_n:
            raise ArtifactError(
                f"artifact {art_dir!r} seals "
                + (f"megastep N={sealed_n}" if sealed_n > 1
                   else "no megastep program")
                + f", megastep={want_mega} was requested; re-export "
                "with export_compiled(..., megastep=N) — the runner "
                "cannot trace one from sealed programs")

        self.manifest = man
        self.workflow = None            # the whole point: no model code
        self.workflow_checksum = man.get("workflow_checksum")
        # embedding rows (None without an Embedding front) — the INPUT
        # token bound, distinct from the output head width self._vocab
        self.input_vocab = man.get("input_vocab")
        self.plan = None
        self._ctx = None
        self.cache_dtype = jnp.dtype(man.get("cache_dtype", "float32"))
        # sealed geometry: slots/l_max/bucket_min — and for v2 paged
        # artifacts the page-pool shape — come from the manifest (the
        # bucket table AND the page-table calling convention are the
        # program inventory, not a config preference).  prefix_reuse is
        # the exporter's record of whether the chain's cached state is
        # pure attention KV; the scheduler-side prefix cache keys off it
        # because the runner has no DecodePlan to inspect.
        self._prefix_ok = bool(man.get("prefix_reuse", False))
        self._init_config(slots=man["slots"], l_max=man["l_max"],
                          window_ms=window_ms, queue_depth=queue_depth,
                          deadline_s=deadline_s,
                          bucket_min=man["bucket_min"],
                          paged=bool(man.get("paged", False)),
                          page_size=man.get("page_size"),
                          pages=man.get("pages"),
                          paged_kernel=bool(man.get("paged_kernel",
                                                    False)),
                          spec=want_spec,
                          spec_k=(int(spec_meta["k"]) if want_spec
                                  else None),
                          megastep=want_mega)
        # v3 calling convention (manifest ``prefill_start``): the sealed
        # prefill programs take the traced ``start``, so chunked prefill
        # and preempt-resume are plain bucket calls on them.  Absent
        # (v1/v2 exports), the dense programs keep the whole-prompt
        # convention and chunking is gated off — an old PAGED program
        # does take ``start``, but its body resets recurrent carry at
        # every call, so mid-prompt continuation is only trusted when
        # the exporter declared it (docs/serving.md "Overload
        # survival").  Overrides the live-builder defaults
        # _init_config just set.
        self._prefill_start = bool(man.get("prefill_start", False))
        self._chunk_capable = self._prefill_start
        # strict: a sealed program that can't AOT-compile here must
        # fail the LOAD, never lazily crash the first request
        self.step_cache = StepCache(strict=True)
        self.status = status

        self._exp_decode = _deserialize(self.art_dir, man, "decode",
                                        progs["decode"])
        # deserialized BEFORE _init_runtime: the base engine compiles
        # the verify program there when spec is on
        self._exp_verify = (
            _deserialize(self.art_dir, man, "verify", progs["verify"])
            if want_spec else None)
        # same load-before-_init_runtime ordering: the base engine
        # compiles the megastep program there when megastep > 1
        self._exp_mega = (
            _deserialize(self.art_dir, man, "megastep",
                         progs["megastep"])
            if want_mega > 1 else None)
        self._exp_prefill = {
            int(pb): _deserialize(self.art_dir, man, f"prefill_{pb}", q)
            for pb, q in progs.get("prefill", {}).items()}
        self._exp_forward = (
            _deserialize(self.art_dir, man, "forward", progs["forward"])
            if "forward" in progs else None)

        parts = load_artifact_weights(self.art_dir, man, verify=False)
        self.wstate = {"params": jax.device_put(parts["params"]),
                       "state": jax.device_put(parts["state"])}
        self._init_runtime(self.wstate["params"])
        # prefill programs are deserialized already; compile them ALL at
        # boot so the counters never move once traffic flows (the live
        # engine compiles buckets lazily; a sealed artifact knows its
        # whole inventory up front)
        for pb in sorted(self._exp_prefill):
            self._prefill_fn(pb, self.wstate["params"])
        self._forward = None
        if self._exp_forward is not None:
            args = (self._sds(self.wstate["params"]),
                    self._sds(self.wstate["state"]),
                    jax.ShapeDtypeStruct(
                        tuple(man["input_spec"]["shape"]),
                        jnp.dtype(man["input_spec"]["dtype"])))
            self._forward, _, _ = self.step_cache.get_step(
                "forward", (man["input_spec"]["shape"][0],),
                lambda: (jax.jit(self._exp_forward.call), None, None),
                args)
        self.info(
            "artifact %s: %d programs (%d prefill buckets%s%s%s), "
            "vocab=%s, %d compiles at load",
            self.art_dir, len(self._exp_prefill) + 1
            + (self._exp_forward is not None)
            + (self._exp_verify is not None)
            + (self._exp_mega is not None),
            len(self._exp_prefill),
            ", forward" if self._exp_forward is not None else "",
            f", verify k={self.spec_k}" if self._exp_verify is not None
            else "",
            f", megastep n={self.megastep}"
            if self._exp_mega is not None else "",
            man.get("vocab"), self.step_cache.compiles)

    # -- program hooks (everything else is the engine, unchanged) -----------
    def _make_caches(self, params):
        return _zeros_from_rows(self.manifest.get("caches", []))

    def _head_width(self, params) -> int:
        vocab = self.manifest.get("vocab")
        if vocab is None:
            raise ArtifactError(
                "artifact manifest records no vocab — it predates the "
                "decode leg; re-export with export_compiled")
        return int(vocab)

    def _compile_decode(self, params):
        step, _, _ = self.step_cache.get_step(
            "decode", self._geometry_key(),
            lambda: (jax.jit(self._exp_decode.call,
                             donate_argnums=(1, 2)), None, None),
            self._decode_args_sds(params), pin=(self._exp_decode,))
        return step

    def _compile_verify(self, params):
        step, _, _ = self.step_cache.get_step(
            "verify", self._geometry_key() + ("k", self.spec_k),
            lambda: (jax.jit(self._exp_verify.call,
                             donate_argnums=(1, 2)), None, None),
            self._verify_args_sds(params), pin=(self._exp_verify,))
        return step

    def _compile_megastep(self, params):
        step, _, _ = self.step_cache.get_step(
            "megastep", self._geometry_key() + ("mega", self.megastep),
            lambda: (jax.jit(self._exp_mega.call,
                             donate_argnums=(1, 2)), None, None),
            self._decode_args_sds(params), pin=(self._exp_mega,))
        return step

    def _prefill_fn(self, pb: int, params, full_ctx: bool = True):
        # ``full_ctx`` is a live-builder compile choice; a sealed
        # inventory has exactly one program per bucket (v3 seals the
        # chunk-capable full-context form, v1/v2 their whole-prompt
        # convention), so the hint is accepted and ignored
        exp = self._exp_prefill.get(int(pb))
        if exp is None:
            raise ArtifactError(
                f"artifact has no prefill program for bucket {pb} "
                f"(inventory: {sorted(self._exp_prefill)}) — the "
                "manifest's bucket table is the sealed program set")
        step, _, _ = self.step_cache.get_step(
            "prefill", (pb,) + self._geometry_key(),
            lambda: (jax.jit(exp.call, donate_argnums=(1, 2)),
                     None, None),
            self._prefill_args_sds(params, pb), pin=(exp,))
        return step

    # -- forward leg ---------------------------------------------------------
    @property
    def has_forward(self) -> bool:
        return self._forward is not None

    def predict(self, wstate, batch):
        """``make_predict_step`` contract over the exported forward
        program — drop-in for RestfulServer's ``predict_fn`` (the
        wstate argument keeps hot swaps visible: the server passes its
        own live reference, which the deploy flip replaces)."""
        if self._forward is None:
            raise ArtifactError(
                "artifact was exported without a forward program")
        return self._forward(wstate["params"], wstate.get("state") or {},
                             batch["@input"])

    def stats(self) -> dict:
        st = super().stats()
        st["artifact"] = {
            "dir": self.art_dir,
            "workflow": self.manifest.get("workflow"),
            "checksum": (self.workflow_checksum or "")[:12],
            "jax_version": self.manifest.get("jax_version"),
            "programs": len(self._exp_prefill) + 1
            + (self._exp_forward is not None)
            + (self._exp_verify is not None)
            + (self._exp_mega is not None),
        }
        return st
