"""RESTful inference serving.

Reference parity: the RESTfulAPI unit + RestfulLoader (reference:
veles/restful_api.py:78 — Twisted HTTP POST endpoint feeding a live
workflow; veles/loader/restful.py:52).

TPU redesign: a stdlib ThreadingHTTPServer wrapping a compiled predict
step. POST /predict {"input": [[...]]} -> {"output": [[...]]}. Requests
batch-pad to the compiled batch size (XLA static shapes); an optional
normalizer denormalizes outputs (reference: inference-time denorm via
normalizer state)."""

from __future__ import annotations

import http.server
import json
import threading
from typing import Callable, Optional

import numpy as np

from ..logger import Logger


class RestfulServer(Logger):
    def __init__(self, predict_fn: Callable, wstate, batch_size: int,
                 input_shape, *, port: int = 0, host: str = "127.0.0.1",
                 normalizer=None, denormalizer=None):
        self.predict_fn = predict_fn
        self.wstate = wstate
        self.batch_size = int(batch_size)
        self.input_shape = tuple(input_shape)
        self.normalizer = normalizer
        self.denormalizer = denormalizer
        outer = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_POST(self):
                if self.path.rstrip("/") != "/predict":
                    self.send_error(404)
                    return
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    req = json.loads(self.rfile.read(n))
                    x = np.asarray(req["input"], np.float32)
                    out = outer.infer(x)
                    body = json.dumps({"output": out.tolist()}).encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                except (KeyError, ValueError, json.JSONDecodeError) as e:
                    body = json.dumps({"error": str(e)}).encode()
                    self.send_response(400)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)

            def log_message(self, *args):
                pass

        self.httpd = http.server.ThreadingHTTPServer((host, port), Handler)
        self.port = self.httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def infer(self, x: np.ndarray) -> np.ndarray:
        if x.shape[1:] != self.input_shape:
            raise ValueError(
                f"input shape {x.shape[1:]} != expected {self.input_shape}")
        if self.normalizer is not None:
            x = self.normalizer.normalize(x)
        outs = []
        bs = self.batch_size
        for i in range(0, len(x), bs):
            chunk = x[i:i + bs]
            valid = len(chunk)
            if valid < bs:  # pad to the compiled batch size
                chunk = np.concatenate(
                    [chunk, np.zeros((bs - valid,) + self.input_shape,
                                     np.float32)])
            y = np.asarray(self.predict_fn(
                self.wstate, {"@input": chunk}))[:valid]
            outs.append(y)
        out = np.concatenate(outs)
        if self.denormalizer is not None:
            out = self.denormalizer.denormalize(out)
        return out

    def start(self):
        self._thread = threading.Thread(target=self.httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        self.info("RESTful inference on http://127.0.0.1:%d/predict",
                  self.port)
        return self

    def stop(self):
        self.httpd.shutdown()
        self.httpd.server_close()
