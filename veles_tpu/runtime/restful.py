"""RESTful inference serving.

Reference parity: the RESTfulAPI unit + RestfulLoader (reference:
veles/restful_api.py:78 — Twisted HTTP POST endpoint feeding a live
workflow; veles/loader/restful.py:52).

TPU redesign: a stdlib ThreadingHTTPServer wrapping a compiled predict
step. POST /predict {"input": [[...]]} -> {"output": [[...]]}. Requests
batch-pad to the compiled batch size (XLA static shapes); an optional
normalizer denormalizes outputs (reference: inference-time denorm via
normalizer state).

Round 4: pass ``workflow=`` to also serve POST /generate
{"prompt": [[ids]], "steps": N, "temperature": t, "top_k": k,
"top_p": p, "eos_id": E, "seed": s} -> {"tokens": [[...]]} — the
KV-cached / carried-state decode of runtime/generate.py behind HTTP —
or deterministic beam search with {"beams": W, "eos_id": E,
"length_penalty": a} -> {"tokens": ..., "scores": [...]} (the
reference's RESTful API was forward-only; its framework had no
sequence models to decode).

Pass ``engine=`` (a started or startable
:class:`~veles_tpu.runtime.engine.DecodeEngine`) to serve non-beam
/generate through the continuous-batching engine instead of per-request
``generate()`` calls: concurrent requests share slots mid-flight, the
program set is fixed for the engine lifetime, queue overflow — and,
under the paged KV cache, PAGE-POOL exhaustion at low slot occupancy —
answers **429 with a Retry-After header** (the backpressure contract of
docs/serving.md) whose hint is adaptive — queue-wait EWMA scaled by how
far the SLO-driven admission controller has closed its window — so
clients back off proportionally to actual congestion.  Requests carry a
class via the ``X-Priority`` header or the body's ``"priority"`` key
(0 = highest, the default; docs/serving.md "Overload survival"):
higher classes queue-jump, may preempt lower-class slots, and are shed
last.  GET /engine exposes the live gauges, including
the ``pages`` group (free/used/cached pages, prefix-cache hit rate,
tokens resident, evictions, copy-on-write admissions) when the engine
runs the paged layout.  Request bodies are capped at
``root.common.serve.max_body_mb`` (413 beyond it — the
snapshot_http_max_mb pattern applied to the ingress side).
``GET /kv/pages?hashes=hex,...`` (or ``?top=K`` for the hottest
cached pages) and ``PUT /kv/pages`` serve the serialized KV-page
transfer path between replicas (docs/serving.md "Disaggregated
prefill/decode") under the same ingress cap; dense engines answer
400.

Operational endpoints (docs/serving.md "Model lifecycle"): ``GET
/healthz`` (liveness — answers whenever the process serves HTTP, engine
or not) and ``GET /ready`` (200 when the engine is started and nobody
is draining, else 503) are always on.  Attaching a
:class:`~veles_tpu.runtime.deploy.DeployController` (it sets
``server.deploy``) additionally routes ``GET /models`` (the versioned
registry) plus ``POST /admin/reload`` (hot weight swap; 409 with the
old version still serving on any load/signature failure) and ``POST
/admin/drain`` (graceful drain, async — 202).  A draining or stopped
engine answers ``/generate`` with 503."""

from __future__ import annotations

import http.server
import json
import threading
from typing import Callable, Optional
from urllib.parse import parse_qs, urlsplit

import numpy as np

from ..config import root
from ..experiments.manager import handle_experiments_request
from ..logger import Logger
from .artifact import ArtifactError
from .engine import EngineOverloaded, EngineStopped, SchedulerCrashed
from .jobs import JobManager, handle_jobs_request
from .memory import memory_monitor
from .metrics import registry, span_ring
from .profiler import serve_profile_post
from .slo import slo_tracker
from .snapshotter import SnapshotCorruptError


def reply_json(handler, obj, code=200, headers=()):
    """One JSON reply for every stdlib HTTP handler in the serving
    stack (this server and the fleet router's front) — body, content
    headers, any extras, done."""
    body = json.dumps(obj).encode()
    handler.send_response(code)
    handler.send_header("Content-Type", "application/json")
    handler.send_header("Content-Length", str(len(body)))
    for k, v in headers:
        handler.send_header(k, v)
    handler.end_headers()
    handler.wfile.write(body)


def reply_metrics_text(handler):
    """The Prometheus text exposition reply (``GET /metrics``) both
    servers — and the fleet router's front — serve identically: one
    place owns the content type and framing."""
    body = registry().render().encode()
    handler.send_response(200)
    handler.send_header("Content-Type",
                        "text/plain; version=0.0.4; charset=utf-8")
    handler.send_header("Content-Length", str(len(body)))
    handler.end_headers()
    handler.wfile.write(body)


def read_json_body(handler):
    """Shared POST ingress: clamp a negative Content-Length (a raw
    ``rfile.read(-1)`` would pin the handler thread until the client
    hangs up), enforce the ``root.common.serve.max_body_mb`` cap
    *before* reading the body into memory (the snapshot_http_max_mb
    pattern on the ingress side), and parse JSON.  Returns the parsed
    dict, or None after replying 413 itself.  JSON errors propagate to
    the caller's 400 mapping."""
    n = max(int(handler.headers.get("Content-Length", 0)), 0)
    cap = int(float(root.common.serve.get("max_body_mb", 64)) * 2 ** 20)
    if n > cap:
        reply_json(handler,
                   {"error": f"request body {n} bytes exceeds the "
                             f"{cap} byte cap "
                             "(root.common.serve.max_body_mb)"},
                   code=413)
        return None
    return json.loads(handler.rfile.read(n)) if n else {}


class RestfulServer(Logger):
    def __init__(self, predict_fn: Callable, wstate, batch_size: int,
                 input_shape, *, port: int = 0, host: str = "127.0.0.1",
                 normalizer=None, denormalizer=None, workflow=None,
                 engine=None, input_dtype=np.float32,
                 default_eos_id=None, vocab_size=None, jobs_dir=None,
                 experiments=None):
        self.predict_fn = predict_fn
        self.wstate = wstate
        self.batch_size = int(batch_size)
        self.input_shape = tuple(input_shape)
        self.input_dtype = np.dtype(input_dtype)  # int32 for token LMs
        self.normalizer = normalizer
        self.denormalizer = denormalizer
        self.workflow = workflow  # enables POST /generate (module doc)
        self.engine = engine      # continuous-batching /generate path
        # server-level eos for requests that don't name one — how a
        # compiled artifact's sealed eos metadata reaches serving
        self.default_eos_id = (None if default_eos_id is None
                               else int(default_eos_id))
        # input-vocab bound for workflow-less serving (an artifact
        # manifest's recorded embedding rows) — keeps the /predict
        # out-of-vocab 400 alive when there is no workflow to scan
        self.vocab_size = (None if vocab_size is None
                           else int(vocab_size))
        self.deploy = None        # set by DeployController (lifecycle ops)
        # batch lane (docs/serving.md "Batch lane"): a jobs_dir turns
        # on the durable job API (/jobs*) against THIS replica's
        # engine — dispatch stays in-process through decode(), so the
        # 429/400/5xx mapping is byte-identical to the HTTP path the
        # fleet-level manager rides
        self.jobs: Optional[JobManager] = None
        if jobs_dir and engine is not None:
            self.jobs = JobManager(jobs_dir, self._local_dispatch)
        # experiment control plane (docs/experiments.md): an attached
        # ExperimentManager serves /experiments* from this replica.
        # Unlike self.jobs the manager is owned by the caller (it may
        # be shared fleet-wide), so this server only routes to it —
        # lifecycle stays with whoever constructed it.
        self.experiments = experiments
        outer = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def _reply(self, obj, code=200, headers=()):
                reply_json(self, obj, code=code, headers=headers)

            def do_GET(self):
                path = self.path.split("?", 1)[0].rstrip("/")
                if path == "/metrics":
                    # Prometheus text exposition on the SERVING port:
                    # the scrape target needs no second server
                    # (docs/observability.md "Metrics & tracing")
                    reply_metrics_text(self)
                    return
                if path == "/slo.json":
                    # rolling-window latency percentiles + burn rates
                    # (runtime/slo.py) — "is the service meeting its
                    # target NOW", which the since-boot histograms on
                    # /metrics cannot answer
                    self._reply(slo_tracker().doc())
                    return
                if path == "/memory.json":
                    # device HBM truth + the aval-derived component
                    # ledger (runtime/memory.py)
                    self._reply(memory_monitor().doc())
                    return
                if path == "/trace.json":
                    # per-request serving timelines (queue-wait →
                    # prefill → decode) as Chrome-trace/Perfetto JSON;
                    # default=repr because span args are arbitrary
                    # host objects (event payloads)
                    body = json.dumps(span_ring().chrome_trace(),
                                      default=repr).encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                if path == "/healthz":
                    # liveness: answers whenever the process serves HTTP
                    # at all — deliberately ignores engine/drain state
                    # (a draining server is alive, just not ready)
                    self._reply({"status": "alive"})
                    return
                if path == "/ready":
                    ok, why = outer.readiness()
                    self._reply({"ready": ok, "reason": why},
                                code=200 if ok else 503)
                    return
                if path == "/models" and outer.deploy is not None:
                    self._reply(outer.deploy.models_doc())
                    return
                if path == "/engine" and outer.engine is not None:
                    self._reply(outer.engine.stats())
                    return
                if path == "/kv/pages" and outer.engine is not None:
                    # serialized prefix-page export (docs/serving.md
                    # "Disaggregated prefill/decode"): ?hashes=hex,hex
                    # names pages by their chained prefix digests;
                    # ?top=K ships the K hottest cached pages (the
                    # rolling drain's pre-warm set).  Dense engines
                    # answer 400 — loud rejection, not an empty blob.
                    q = parse_qs(urlsplit(self.path).query)
                    try:
                        if "top" in q:
                            hashes = outer.engine.hot_page_hashes(
                                int(q["top"][0]))
                        else:
                            hashes = [h for part in q.get("hashes", [])
                                      for h in part.split(",") if h]
                        blob = outer.engine.export_pages(hashes)
                    except ValueError as e:
                        self._reply({"error": str(e)}, code=400)
                        return
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     "application/octet-stream")
                    self.send_header("Content-Length", str(len(blob)))
                    self.end_headers()
                    self.wfile.write(blob)
                    return
                hit = handle_jobs_request(outer.jobs, "GET",
                                          self.path, None)
                if hit is None:
                    hit = handle_experiments_request(
                        outer.experiments, "GET", self.path, None)
                if hit is not None:
                    self._reply(hit[1], code=hit[0])
                    return
                self.send_error(404)

            def do_DELETE(self):
                # DELETE /jobs/<id>: cancel a batch job — queued work
                # drops immediately; its trough-class slots are
                # interactive traffic's to reclaim anyway.
                # DELETE /experiments/<id>: cancel an experiment.
                hit = handle_jobs_request(outer.jobs, "DELETE",
                                          self.path, None)
                if hit is None:
                    hit = handle_experiments_request(
                        outer.experiments, "DELETE", self.path, None)
                if hit is not None:
                    self._reply(hit[1], code=hit[0])
                    return
                self.send_error(404)

            def do_PUT(self):
                # PUT /kv/pages: import a peer's serialized prefix
                # pages.  Raw octet-stream body under the SAME
                # root.common.serve.max_body_mb ingress cap as JSON
                # POSTs (413 beyond it); every validation defect —
                # bad magic, geometry or weights-version mismatch,
                # integrity failure, dense layout — is the client's
                # 400, never a silently-poisoned prefix cache.
                path = self.path.split("?", 1)[0].rstrip("/")
                if path != "/kv/pages":
                    self.send_error(404)
                    return
                if outer.engine is None:
                    self._reply(
                        {"error": "KV-page transfer needs engine= "
                                  "serving (see docs/serving.md "
                                  '"Disaggregated prefill/decode")'},
                        code=404)
                    return
                n = max(int(self.headers.get("Content-Length", 0)), 0)
                cap = int(float(root.common.serve.get(
                    "max_body_mb", 64)) * 2 ** 20)
                if n > cap:
                    self._reply(
                        {"error": f"request body {n} bytes exceeds "
                                  f"the {cap} byte cap "
                                  "(root.common.serve.max_body_mb)"},
                        code=413)
                    return
                blob = self.rfile.read(n)
                try:
                    self._reply(outer.engine.import_pages(blob))
                except ValueError as e:
                    self._reply({"error": str(e)}, code=400)
                except TimeoutError as e:
                    self._reply({"error": str(e)}, code=504)
                except Exception as e:  # noqa: BLE001 — server answers
                    self._reply({"error": f"{type(e).__name__}: {e}"},
                                code=500)

            def do_POST(self):
                path = self.path.split("?", 1)[0].rstrip("/")
                admin = path in ("/admin/reload", "/admin/drain",
                                 "/admin/stage", "/admin/commit",
                                 "/admin/abort")
                if path == "/debug/profile":
                    # duration-bounded on-demand jax.profiler capture:
                    # the shared handler (runtime/profiler.py) owns the
                    # ingress cap and the 409/400/500 mapping for both
                    # servers; the handler blocks for the capture
                    # (worker thread; other requests keep flowing)
                    code, obj = serve_profile_post(self.headers,
                                                   self.rfile)
                    self._reply(obj, code=code)
                    return
                if path == "/jobs" or path.startswith("/jobs/") \
                        or path == "/experiments" \
                        or path.startswith("/experiments/"):
                    try:
                        body = read_json_body(self)  # cap -> 413 inside
                    except json.JSONDecodeError as e:
                        self._reply({"error": str(e)}, code=400)
                        return
                    if body is None:
                        return
                    hit = handle_jobs_request(outer.jobs, "POST",
                                              self.path, body)
                    if hit is None:
                        hit = handle_experiments_request(
                            outer.experiments, "POST", self.path, body)
                    if hit is not None:
                        self._reply(hit[1], code=hit[0])
                        return
                if path not in ("/predict", "/generate") and not admin:
                    self.send_error(404)
                    return
                if admin and outer.deploy is None:
                    self._reply(
                        {"error": "no deploy control plane attached "
                                  "(serve with DeployController / "
                                  "--model-dir; see docs/serving.md)"},
                        code=404)
                    return
                try:
                    req = read_json_body(self)  # cap -> 413 inside
                    if req is None:
                        return
                    if path == "/admin/drain":
                        # async: the reply must not wait for in-flight
                        # slots to retire (202 = drain accepted).  An
                        # optional {"handoff": url} pre-warms that
                        # successor with this engine's hot prefix pages
                        # before the engine stops (docs/serving.md
                        # "Disaggregated prefill/decode").
                        self._reply(outer.deploy.begin_drain(
                            handoff=req.get("handoff")), code=202)
                        return
                    if path in ("/admin/stage", "/admin/commit",
                                "/admin/abort"):
                        # the two-phase half of a COORDINATED fleet
                        # swap (runtime/fleet.py): stage loads +
                        # validates + places without flipping, commit
                        # flips the staged buffer, abort withdraws it.
                        # Same failure mapping as reload: a load/flip
                        # failure is a 409 with the old version still
                        # serving, a malformed request a 400.
                        try:
                            if path == "/admin/stage":
                                source = (req.get("source")
                                          or req.get("path"))
                                if source is None \
                                        and req.get("version") is None:
                                    self._reply(
                                        {"error": 'stage needs '
                                                  '{"path": ...} (or '
                                                  '"source"/"version")'},
                                        code=400)
                                    return
                                self._reply(outer.deploy.stage(
                                    source=source,
                                    version=req.get("version")))
                            elif path == "/admin/commit":
                                token = req.get("token")
                                if not token:
                                    self._reply(
                                        {"error": 'commit needs the '
                                                  '{"token": ...} '
                                                  'stage returned'},
                                        code=400)
                                    return
                                self._reply(
                                    outer.deploy.commit_staged(token))
                            else:
                                self._reply(outer.deploy.abort_staged(
                                    req.get("token")))
                        except KeyError as e:
                            self._reply({"error": str(e)}, code=404)
                        except (ValueError, OSError, TimeoutError,
                                SnapshotCorruptError,
                                ArtifactError) as e:
                            self._reply(
                                {"error": f"{type(e).__name__}: {e}",
                                 "active": outer.deploy.registry
                                 .active_version},
                                code=409)
                        return
                    if path == "/admin/reload":
                        source = req.get("source") or req.get("path")
                        if source is None and req.get("version") is None:
                            # a malformed REQUEST is the client's 400,
                            # not a load-conflict 409
                            self._reply(
                                {"error": 'reload needs {"path": ...} '
                                          '(or "source"/"version")'},
                                code=400)
                            return
                        try:
                            self._reply(outer.deploy.reload(
                                source=source,
                                version=req.get("version")))
                        except KeyError as e:
                            # only the registry's version lookup raises
                            # KeyError here (deploy.reload converts
                            # loader KeyErrors to ValueError)
                            self._reply({"error": str(e)}, code=404)
                        except (ValueError, OSError, TimeoutError,
                                SnapshotCorruptError,
                                ArtifactError) as e:
                            # load/signature/flip-timeout failure —
                            # including a corrupt / version-skewed /
                            # non-artifact source: the old version is
                            # STILL SERVING (the reload contract) —
                            # 409, not a 5xx that would page someone
                            # or a 504 masquerading as a request
                            # deadline.  EngineDraining is NOT caught
                            # here: it falls to the 503 below.
                            self._reply(
                                {"error": f"{type(e).__name__}: {e}",
                                 "active": outer.deploy.registry
                                 .active_version},
                                code=409)
                        return
                    if path == "/generate":
                        # request class (docs/serving.md "Overload
                        # survival"): the X-Priority header is the
                        # proxy-friendly spelling of the body key; an
                        # explicit body "priority" wins
                        hdr = self.headers.get("X-Priority")
                        if hdr is not None:
                            req.setdefault("priority", hdr)
                        if req.get("stream"):
                            # incremental NDJSON frames (docs/serving.md
                            # "Streaming and mid-stream failover").
                            # Validation/submit errors raise BEFORE any
                            # header is written, so they ride the same
                            # status mapping below as the unary path.
                            outer.stream_generate(req, self)
                            return
                        self._reply(outer.decode(req))
                        return
                    self._reply(
                        {"output": outer.infer(req["input"]).tolist()})
                except EngineOverloaded as e:
                    # the hint is ADAPTIVE (queue-wait EWMA x how far
                    # the admission controller closed the window —
                    # engine._retry_after), so clients back off
                    # proportionally to actual congestion; the body
                    # carries the un-rounded seconds for programmatic
                    # clients
                    self._reply(
                        {"error": str(e),
                         "retry_after_s": round(e.retry_after_s, 3)},
                        code=429,
                        headers=(("Retry-After",
                                  str(int(round(e.retry_after_s)))),))
                except SchedulerCrashed as e:
                    # the scheduler loop died: this request (queued or
                    # mid-flight when it happened, or submitted after)
                    # FAILED — a clear 500 that pages someone, never the
                    # 503 a balancer would politely route around
                    self._reply({"error": str(e),
                                 "kind": "scheduler_crash"}, code=500)
                except EngineStopped as e:
                    # draining or stopped: refuse new work the way a
                    # load balancer expects (503 + Retry-After), matching
                    # the /ready flip
                    self._reply({"error": str(e)}, code=503,
                                headers=(("Retry-After", "5"),))
                except TimeoutError as e:
                    self._reply({"error": str(e)}, code=504)
                except (KeyError, TypeError, ValueError,
                        json.JSONDecodeError) as e:
                    self._reply({"error": str(e)}, code=400)
                except Exception as e:  # noqa: BLE001 — e.g. an
                    # undecodable chain (WorkflowError); server answers
                    self._reply({"error": f"{type(e).__name__}: {e}"},
                                code=500)

            def log_message(self, *args):
                pass

        self.httpd = http.server.ThreadingHTTPServer((host, port), Handler)
        self.port = self.httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def readiness(self):
        """(ready, reason) for ``GET /ready``: the engine is started and
        nobody is draining.  A plain predict server (no engine) is ready
        once it serves HTTP — liveness and readiness only diverge when
        there is lifecycle state to diverge on.  With
        ``root.common.observe.slo.degrade_ready`` on, a sustained SLO
        burn (runtime/slo.py) also flips readiness so a load balancer
        sheds traffic before the tail melts."""
        if self.deploy is not None and self.deploy.draining:
            return False, "draining"
        if self.engine is not None:
            if self.engine.draining:
                return False, "draining"
            if not self.engine.started:
                return False, "engine not started"
        if slo_tracker().degrading():
            return False, "slo burn-rate over threshold (see /slo.json)"
        return True, "ok"

    def infer(self, x) -> np.ndarray:
        if np.issubdtype(self.input_dtype, np.integer):
            # token-id inputs: int32 narrowing would WRAP huge ids and
            # the embedding lookup silently clips out-of-vocab ones —
            # the same 400-not-wrong-200 contract decode() enforces
            xi = np.asarray(x, np.int64)
            vocab = self._vocab_size()
            hi = vocab if vocab is not None else 2 ** 31
            if xi.size and (xi.min() < 0 or xi.max() >= hi):
                raise ValueError(
                    f"input token ids must be in [0, {hi}) "
                    f"(got min {xi.min()}, max {xi.max()})")
            x = xi.astype(self.input_dtype)
        else:
            x = np.asarray(x, self.input_dtype)
        if x.shape[1:] != self.input_shape:
            raise ValueError(
                f"input shape {x.shape[1:]} != expected {self.input_shape}")
        if self.normalizer is not None:
            x = self.normalizer.normalize(x)
        outs = []
        bs = self.batch_size
        for i in range(0, len(x), bs):
            chunk = x[i:i + bs]
            valid = len(chunk)
            if valid < bs:  # pad to the compiled batch size
                chunk = np.concatenate(
                    [chunk, np.zeros((bs - valid,) + self.input_shape,
                                     self.input_dtype)])
            y = np.asarray(self.predict_fn(
                self.wstate, {"@input": chunk}))[:valid]
            outs.append(y)
        out = np.concatenate(outs)
        if self.denormalizer is not None:
            out = self.denormalizer.denormalize(out)
        return out

    def _vocab_size(self) -> Optional[int]:
        """Embedding-table rows of the served workflow (None when the
        chain has no embedding at the front).  Workflow-less serving —
        a compiled artifact — reads the manifest's recorded embedding
        rows instead (``input_vocab``; NOT the output head width, which
        is no bound on what a non-embedding front accepts)."""
        if self.workflow is None:
            if self.vocab_size is not None:
                return self.vocab_size
            v = getattr(self.engine, "input_vocab", None)
            return int(v) if v else None
        from ..units.nn import input_vocab
        return input_vocab(self.workflow, self.wstate["params"])

    @staticmethod
    def _req_int(v, name):
        """Integral coercion for JSON numerics: 2 / 2.0 / "2" -> 2;
        2.5 / "x" / Infinity / true -> ValueError (the handler's 400
        path).  JSON has no int/float distinction, so whole-valued floats
        must coerce; silent truncation (int(2.7) -> 2) must not, and JSON
        booleans must not ride the float path (float(True) == 1.0 would
        silently accept {"n": true})."""
        if isinstance(v, bool):
            raise ValueError(f"{name} must be an integer, got {v!r}")
        try:
            f = float(v)
            i = int(f)
            if f != i:
                raise ValueError
            return i
        except (TypeError, ValueError, OverflowError):
            raise ValueError(
                f"{name} must be an integer, got {v!r}") from None

    def decode(self, req: dict) -> dict:
        """POST /generate body -> {"tokens": [[...]]} (+ "scores" for
        beam search)."""
        if self.workflow is None and self.engine is None:
            raise ValueError(
                "this server was started without a workflow; /generate "
                "needs RestfulServer(..., workflow=wf) or engine=")
        if req.get("stop") is not None \
                or req.get("emitted_prefix") is not None:
            # silently ignoring either would return a WRONG unary 200
            # (un-stopped tokens / a restarted-from-zero sequence)
            raise ValueError(
                'stop and emitted_prefix ride the streaming path; add '
                '{"stream": true} (docs/serving.md "Streaming and '
                'mid-stream failover")')
        from .generate import generate
        # Coerce once at the boundary: np.asarray(..., int64) would
        # silently TRUNCATE fractional ids (2.7 -> 2) and a float/str
        # passed through raw would crash deep in jnp with an opaque 500.
        prompt = np.asarray(req["prompt"])
        if not np.issubdtype(prompt.dtype, np.integer):
            if (np.issubdtype(prompt.dtype, np.floating)
                    and np.all(np.isfinite(prompt))
                    and np.all(prompt == np.floor(prompt))):
                prompt = prompt.astype(np.int64)  # whole-valued floats ok
            else:
                raise ValueError(
                    "prompt token ids must be integers "
                    f"(got dtype {prompt.dtype})")
        prompt = prompt.astype(np.int64)
        if prompt.ndim != 2 or 0 in prompt.shape:
            raise ValueError("prompt must be a non-empty 2-D "
                             "[[ids], ...] array")
        # int32 narrowing would WRAP huge ids and the embedding lookup
        # silently clips out-of-vocab ones — answer 400, not a wrong 200
        vocab = self._vocab_size()
        hi = vocab if vocab is not None else 2 ** 31
        if prompt.min() < 0 or prompt.max() >= hi:
            raise ValueError(
                f"prompt token ids must be in [0, {hi}) "
                f"(got min {prompt.min()}, max {prompt.max()})")
        steps = self._req_int(req.get("steps", 16), "steps")
        if not 0 < steps <= 65536:
            raise ValueError(f"steps must be in [1, 65536], got {steps}")
        beams = self._req_int(req.get("beams", 1), "beams")
        if beams < 1:
            raise ValueError(f"beams must be >= 1, got {beams}")
        # bound total decode work/cache memory, not just the step
        # count (beam search multiplies every cache by its width)
        B, P = prompt.shape
        if B * beams * (P + steps) > 1_048_576:
            raise ValueError(
                f"request too large: batch {B} x beams {beams} x total "
                f"length {P + steps} exceeds the 2^20 token-cell cap")
        try:
            temperature = float(req.get("temperature", 0.0))
            top_p = req.get("top_p")
            top_p = None if top_p is None else float(top_p)
        except (TypeError, ValueError) as e:
            raise ValueError(
                f"temperature/top_p must be numeric: {e}") from None
        top_k = req.get("top_k")
        if top_k is not None:
            top_k = self._req_int(top_k, "top_k")
        if (top_k is not None or top_p is not None) and temperature <= 0:
            # same contract as the CLI: filters apply to SAMPLING;
            # answering greedy while claiming top-k would mislead
            raise ValueError(
                "top_k/top_p filter sampling and need temperature > 0 "
                "(temperature 0 is greedy decoding)")
        # request class (docs/serving.md "Overload survival"): 0 — the
        # default and highest — through serve.priorities - 1.  Range is
        # the engine's contract (submit raises ValueError -> 400); a
        # server without an engine has no queue to jump, so a non-zero
        # class on the per-request generate() path is refused rather
        # than silently flattened.
        priority = self._req_int(req.get("priority", 0), "priority")
        if priority and self.engine is None:
            raise ValueError(
                "priority classes need engine= serving (per-request "
                "generate() has no queue to prioritize)")
        # batch lane (docs/serving.md "Batch lane"): the trough-filler
        # class below every interactive priority — engine-only (the
        # per-request path has no trough to fill), and exclusive with
        # an explicit priority (batch IS the class)
        batch = bool(req.get("batch", False))
        if batch and self.engine is None:
            raise ValueError(
                "batch-class requests need engine= serving "
                '(docs/serving.md "Batch lane")')
        if batch and priority:
            raise ValueError(
                "batch rides the trough class below every priority; "
                "drop the priority key (or drop batch)")
        eos_id = req.get("eos_id")
        if eos_id is None:
            eos_id = self.default_eos_id  # e.g. the artifact's sealed
        if eos_id is not None:            # eos metadata
            # forward the COERCED value: a float 2.0 would pass the
            # range check then raise TypeError inside generate_beam's
            # .at[eos_id]
            eos_id = self._req_int(eos_id, "eos_id")
            if not 0 <= eos_id < hi:
                # out-of-vocab eos could never fire and would
                # silently disable eos freezing (the native CLI
                # rejects it too)
                raise ValueError(
                    f"eos_id {eos_id} is outside the model "
                    f"vocabulary [0, {hi})")
        if beams > 1:
            if self.workflow is None:
                raise ValueError(
                    "beam search needs the live workflow; compiled-"
                    "artifact serving covers greedy/sampling decode "
                    "(the sealed program set has no beam program)")
            if temperature > 0 or req.get("seed") is not None:
                raise ValueError(
                    "beams is deterministic search; drop temperature/"
                    "top_k/top_p/seed or use beams=1")
            if priority or batch:
                raise ValueError(
                    "beam search runs outside the engine queue; "
                    "priority classes and the batch lane apply to "
                    "beams=1 requests")
            length_penalty = float(req.get("length_penalty", 0.0))
            if length_penalty < 0:
                raise ValueError(
                    f"length_penalty must be >= 0, got {length_penalty}")
            from .generate import generate_beam
            toks, scores = generate_beam(
                self.workflow, self.wstate, prompt.astype(np.int32),
                steps, beams=beams, eos_id=eos_id,
                length_penalty=length_penalty)
            return {"tokens": np.asarray(toks).tolist(),
                    "scores": np.asarray(scores).tolist()}
        if req.get("length_penalty"):
            raise ValueError(
                "length_penalty shapes BEAM scores and needs beams > 1")
        import jax
        key = jax.random.key(self._req_int(req.get("seed", 0), "seed"))
        if self.engine is not None:
            # continuous batching: rows ride slots alongside other
            # requests; rows past their eos come back eos-padded, same
            # as generate(eos_id).  EngineOverloaded propagates to the
            # handler's 429 + Retry-After.
            toks = self.engine.generate(
                prompt.astype(np.int32), steps, temperature=temperature,
                top_k=top_k, top_p=top_p, eos_id=eos_id, key=key,
                priority=priority, batch=batch)
            return {"tokens": np.asarray(toks).tolist()}
        toks = generate(
            self.workflow, self.wstate, prompt.astype(np.int32), steps,
            temperature=temperature, top_k=top_k, top_p=top_p,
            eos_id=eos_id, key=key)
        return {"tokens": np.asarray(toks).tolist()}

    def _stream_submit(self, req: dict):
        """Validate a ``{"stream": true}`` /generate body and submit it
        to the engine's streaming path.  Returns ``(engine_request,
        consumer_timeout_s)``.  Every error raises BEFORE the caller
        writes response headers, so malformed bodies get the normal
        400/429/5xx statuses, never a broken half-stream."""
        if self.engine is None:
            raise ValueError(
                "streaming needs engine= serving (per-request "
                "generate() has no incremental token feed)")
        if self._req_int(req.get("beams", 1), "beams") != 1:
            raise ValueError("streaming supports beams=1 only")
        if req.get("batch"):
            raise ValueError(
                "the batch lane is unary; drop stream or batch")
        prompt = np.asarray(req["prompt"])
        if not np.issubdtype(prompt.dtype, np.integer):
            raise ValueError(
                "prompt token ids must be integers "
                f"(got dtype {prompt.dtype})")
        if prompt.ndim == 2 and prompt.shape[0] == 1:
            prompt = prompt.reshape(-1)
        if prompt.ndim != 1 or prompt.size == 0:
            raise ValueError(
                "a streamed prompt is ONE non-empty sequence: "
                "[ids] or [[ids]]")
        vocab = self._vocab_size()
        hi = vocab if vocab is not None else 2 ** 31
        if prompt.min() < 0 or prompt.max() >= hi:
            raise ValueError(
                f"prompt token ids must be in [0, {hi}) "
                f"(got min {prompt.min()}, max {prompt.max()})")
        steps = self._req_int(req.get("steps", 16), "steps")
        if not 0 < steps <= 65536:
            raise ValueError(f"steps must be in [1, 65536], got {steps}")
        try:
            temperature = float(req.get("temperature", 0.0))
            top_p = req.get("top_p")
            top_p = None if top_p is None else float(top_p)
        except (TypeError, ValueError) as e:
            raise ValueError(
                f"temperature/top_p must be numeric: {e}") from None
        top_k = req.get("top_k")
        if top_k is not None:
            top_k = self._req_int(top_k, "top_k")
        if (top_k is not None or top_p is not None) and temperature <= 0:
            raise ValueError(
                "top_k/top_p filter sampling and need temperature > 0 "
                "(temperature 0 is greedy decoding)")
        priority = self._req_int(req.get("priority", 0), "priority")
        eos_id = req.get("eos_id")
        if eos_id is None:
            eos_id = self.default_eos_id
        if eos_id is not None:
            eos_id = self._req_int(eos_id, "eos_id")
            if not 0 <= eos_id < hi:
                raise ValueError(
                    f"eos_id {eos_id} is outside the model "
                    f"vocabulary [0, {hi})")
        # the crash-safe resume form (engine.submit): the ORIGINAL
        # prompt/steps/seed plus the tokens an interrupted stream
        # already delivered — the engine re-prefills prompt + prefix
        # and continues bitwise-identically
        pref = req.get("emitted_prefix")
        if pref is not None:
            pref = np.asarray(pref)
            if pref.size and not np.issubdtype(pref.dtype, np.integer):
                raise ValueError("emitted_prefix must hold integer "
                                 "token ids")
            pref = pref.reshape(-1).astype(np.int64)
            if pref.size and (pref.min() < 0 or pref.max() >= hi):
                raise ValueError(
                    f"emitted_prefix token ids must be in [0, {hi})")
            pref = pref.astype(np.int32)
        stop = req.get("stop")
        if stop is not None:
            if not isinstance(stop, (list, tuple)):
                raise ValueError(
                    'stop must be a list of token-id sequences, e.g. '
                    '{"stop": [[13, 198]]}')
            stop = [np.asarray(s, np.int64).reshape(-1) for s in stop]
            for s in stop:
                if s.size and (s.min() < 0 or s.max() >= hi):
                    raise ValueError(
                        f"stop token ids must be in [0, {hi})")
            stop = [s.astype(np.int32) for s in stop]
        deadline_s = req.get("deadline_s")
        if deadline_s is not None:
            deadline_s = float(deadline_s)
            if not deadline_s > 0:
                raise ValueError(
                    f"deadline_s must be > 0, got {deadline_s}")
        import jax
        key = jax.random.key(self._req_int(req.get("seed", 0), "seed"))
        r = self.engine.submit(
            prompt.astype(np.int32), steps, temperature=temperature,
            top_k=top_k, top_p=top_p, eos_id=eos_id, key=key,
            deadline_s=deadline_s, priority=priority, stream=True,
            emitted_prefix=pref, stop=stop)
        # the consumer timeout is a hang-guard over the ENGINE-enforced
        # deadline, not a second deadline: slack covers the terminal
        # frame's delivery
        wait = (deadline_s if deadline_s is not None
                else self.engine.deadline_s) + 30.0
        return r, wait

    def stream_generate(self, req: dict, handler):
        """POST /generate with ``{"stream": true}``: one NDJSON line
        per token frame — ``{"i": n, "token": t}`` with ``i`` the
        GLOBAL generated-token index — then exactly one terminal line
        ``{"done": true, "finish_reason": ..., "usage": {...}}``
        (+ ``"error"`` when the reason is error/deadline).  The reply
        closes the connection to frame the stream (the handler speaks
        HTTP/1.0); a resume via ``emitted_prefix`` numbers its first
        frame one past the prefix, which is what lets the fleet router
        splice failover streams gaplessly (docs/serving.md "Streaming
        and mid-stream failover")."""
        r, wait = self._stream_submit(req)
        h = r.stream
        handler.send_response(200)
        handler.send_header("Content-Type", "application/x-ndjson")
        handler.send_header("Cache-Control", "no-store")
        handler.send_header("Connection", "close")
        handler.end_headers()
        try:
            for ev in h.events(timeout_s=wait):
                if ev[0] == "token":
                    line = {"i": ev[1], "token": ev[2]}
                else:
                    _, reason, err = ev
                    line = {"done": True, "finish_reason": reason,
                            "usage": {
                                "prompt_tokens": h.prompt_tokens,
                                "completion_tokens": int(h.next_i)}}
                    if err is not None:
                        line["error"] = err
                handler.wfile.write(
                    (json.dumps(line) + "\n").encode())
                handler.wfile.flush()
        except TimeoutError:
            # hang-guard tripped (a dead scheduler with the handle
            # still open): best-effort terminal frame, then close
            try:
                handler.wfile.write((json.dumps(
                    {"done": True, "finish_reason": "error",
                     "error": "stream stalled past its deadline"})
                    + "\n").encode())
            except OSError:
                pass
        except (BrokenPipeError, ConnectionError, OSError):
            # consumer went away mid-stream: nothing to reply to; the
            # request itself keeps running and retires unary (the
            # bounded handle buffer caps what it can accumulate)
            pass

    def _local_dispatch(self, body: dict):
        """The job manager's in-process dispatch against THIS replica:
        decode() with the handler's exact exception->status mapping, as
        a ``(status, doc, headers)`` triple — the same shape the fleet
        router's ``handle_generate`` returns, so :class:`JobManager`
        cannot tell a single replica from a fleet."""
        try:
            return 200, self.decode(body), ()
        except EngineOverloaded as e:
            return 429, {"error": str(e),
                         "retry_after_s": round(e.retry_after_s, 3)}, ()
        except SchedulerCrashed as e:
            return 500, {"error": str(e),
                         "kind": "scheduler_crash"}, ()
        except EngineStopped as e:
            return 503, {"error": str(e)}, ()
        except TimeoutError as e:
            return 504, {"error": str(e)}, ()
        except (KeyError, TypeError, ValueError,
                json.JSONDecodeError) as e:
            return 400, {"error": str(e)}, ()

    def start(self):
        if self.engine is not None and not self.engine.started:
            self.engine.start()
        if self.jobs is not None:
            self.jobs.start()
        self._thread = threading.Thread(target=self.httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        self.info("RESTful inference on http://127.0.0.1:%d/predict",
                  self.port)
        return self

    def stop(self):
        if self.jobs is not None:
            # stop scheduling batch dispatches BEFORE the engine goes
            # away; committed results survive for the next manager
            self.jobs.stop()
        self.httpd.shutdown()
        self.httpd.server_close()
        if self.engine is not None:
            self.engine.stop()
