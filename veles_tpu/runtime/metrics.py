"""Metrics core: Counter / Gauge / Histogram primitives in a
process-global registry, Prometheus text exposition, and a bounded span
ring exportable as a Chrome-trace / Perfetto JSON timeline.

Reference parity: the L10 observability stack (reference:
veles/web_status.py Tornado+MongoDB status, veles/logger.py:264 MongoDB
event tracing, veles/units.py:805-817 per-unit timing) sampled gauges
and logged events but never measured *distributions* — and neither did
this rebuild until now: the engine exposed a single ``tokens_per_sec``
gauge, so no perf PR could be judged against a tail-latency baseline.

Design rules (docs/observability.md "Metrics & tracing"):

* **zero dependencies** — stdlib only, no prometheus_client; the text
  format is ~40 lines to emit and every scraper speaks it;
* **host-side only** — nothing here may be called from traced scope
  (the analyzer's VT103 rule enforces it: ``time``/IO inside a traced
  program is flagged at lint time);
* **small-cardinality labels** — label sets are bounded per metric
  (``root.common.observe.label_cap``); past the cap new label values
  collapse into a single ``_other`` series and are counted in
  ``vt_metrics_dropped_labels_total``, because an unbounded label value
  (e.g. a request id) turns a metrics page into a memory leak;
* **fixed buckets** — histograms are fixed-bucket (Prometheus
  semantics: cumulative ``_bucket{le=...}`` counts + ``_sum`` +
  ``_count``), so merging across processes and computing quantiles
  after the fact both stay trivial;
* **one registry** — the ad-hoc gauges (``engine.stats()``, StepCache
  compile counters, deploy swap history) feed the SAME registry the
  ``/metrics`` endpoint renders, so status.json, ``GET /engine`` and
  ``GET /metrics`` present one consistent view.

The span ring is the request-level half: bounded (``root.common
.observe.span_ring``), host-timestamped spans — per-request serving
timelines (queue-wait → prefill → decode), per-epoch training spans,
status events as instants — served as ``GET /trace.json`` and written
by ``--trace-out``, loadable directly in Perfetto / ``chrome://tracing``.
"""

from __future__ import annotations

import collections
import itertools
import json
import re
import threading
import time
from typing import Dict, List, Optional, Tuple

from ..config import root

#: default latency buckets (seconds): sub-ms prefills on warm caches up
#: to the engine's 60s retry ceiling; chosen so TTFT, queue-wait and
#: decode-step distributions all land mid-range instead of saturating
#: an end bucket.
DEFAULT_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                   0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0)

#: the label-set a metric past its cardinality cap collapses into.
OVERFLOW_LABEL = "_other"


def _escape_help(s: str) -> str:
    return s.replace("\\", "\\\\").replace("\n", "\\n")


def _escape_label(s: str) -> str:
    return (s.replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _fmt(v) -> str:
    """Prometheus sample value: integral floats render as ints (bucket
    counts), the rest as shortest-repr floats."""
    if isinstance(v, bool):
        return str(int(v))
    if isinstance(v, float):
        if v != v:          # NaN
            return "NaN"
        if v in (float("inf"), float("-inf")):
            return "+Inf" if v > 0 else "-Inf"
        if v == int(v) and abs(v) < 1e15:
            return str(int(v))
        return repr(v)
    return str(v)


class _Metric:
    """Shared parent for the three kinds: owns the name, help text,
    label names, and the children table (one child per label-value
    tuple; the empty tuple is the label-less default child)."""

    kind = "untyped"

    def __init__(self, name: str, help: str, labelnames: Tuple[str, ...],
                 label_cap: int):
        if not re.fullmatch(r"[a-zA-Z_:][a-zA-Z0-9_:]*", name):
            raise ValueError(f"invalid metric name {name!r}")
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self.label_cap = max(1, int(label_cap))
        self._lock = threading.Lock()
        self._children: Dict[Tuple[str, ...], object] = {}  # guarded-by: self._lock
        self._dropped = None        # registry's overflow counter child
        if not self.labelnames:
            with self._lock:
                self._children[()] = self._make_child()

    def _make_child(self):
        raise NotImplementedError

    def labels(self, **kv):
        """The child series for one label-value assignment.  Values are
        stringified; an unseen assignment past the cardinality cap
        collapses into the ``_other`` series (and is counted) instead
        of growing the table without bound."""
        if set(kv) != set(self.labelnames):
            raise ValueError(
                f"{self.name} takes labels {self.labelnames}, "
                f"got {tuple(kv)}")
        key = tuple(str(kv[n]) for n in self.labelnames)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                if len(self._children) >= self.label_cap:
                    key = (OVERFLOW_LABEL,) * len(self.labelnames)
                    child = self._children.get(key)
                    if child is None:
                        child = self._children[key] = self._make_child()
                    dropped = self._dropped
                else:
                    child = self._children[key] = self._make_child()
                    dropped = None
            else:
                dropped = None
        if dropped is not None:
            dropped.inc()
        return child

    def _default(self):
        if self.labelnames:
            raise ValueError(
                f"{self.name} is labelled {self.labelnames}; "
                "call .labels(...) first")
        with self._lock:
            return self._children[()]

    def series_count(self) -> int:
        with self._lock:
            return len(self._children)

    def _snapshot(self) -> List[Tuple[Tuple[str, ...], object]]:
        with self._lock:
            return list(self._children.items())


class _CounterChild:
    __slots__ = ("_lock", "_v")

    def __init__(self, lock):
        self._lock = lock
        self._v = 0.0  # guarded-by: self._lock

    def inc(self, amount: float = 1.0):
        if amount < 0:
            raise ValueError("counters only go up; use a Gauge")
        with self._lock:
            self._v += amount

    @property
    def value(self) -> float:
        with self._lock:
            return self._v


class Counter(_Metric):
    """Monotonic count.  ``inc()`` on the label-less default, or
    ``labels(outcome="ok").inc()``."""

    kind = "counter"

    def _make_child(self):
        return _CounterChild(self._lock)

    def inc(self, amount: float = 1.0):
        self._default().inc(amount)

    @property
    def value(self) -> float:
        return self._default().value


class _GaugeChild:
    __slots__ = ("_lock", "_v")

    def __init__(self, lock):
        self._lock = lock
        self._v = 0.0  # guarded-by: self._lock

    def set(self, v: float):
        with self._lock:
            self._v = float(v)

    def inc(self, amount: float = 1.0):
        with self._lock:
            self._v += amount

    def dec(self, amount: float = 1.0):
        self.inc(-amount)

    @property
    def value(self) -> float:
        with self._lock:
            return self._v


class Gauge(_Metric):
    """Point-in-time value; ``set()`` wins over inc/dec for sampled
    gauges (occupancy, queue depth)."""

    kind = "gauge"

    def _make_child(self):
        return _GaugeChild(self._lock)

    def set(self, v: float):
        self._default().set(v)

    def inc(self, amount: float = 1.0):
        self._default().inc(amount)

    def dec(self, amount: float = 1.0):
        self._default().dec(amount)

    @property
    def value(self) -> float:
        return self._default().value


class _HistogramChild:
    __slots__ = ("_lock", "uppers", "_counts", "_sum", "_count")

    def __init__(self, lock, uppers):
        self._lock = lock
        self.uppers = uppers            # finite upper bounds, ascending
        self._counts = [0] * (len(uppers) + 1)  # guarded-by: self._lock
        self._sum = 0.0  # guarded-by: self._lock
        self._count = 0  # guarded-by: self._lock

    def observe(self, v: float):
        v = float(v)
        # linear scan: bucket lists are ~16 long and the scan is
        # lock-held for nanoseconds; bisect would save nothing
        i = 0
        for u in self.uppers:
            if v <= u:
                break
            i += 1
        with self._lock:
            self._counts[i] += 1
            self._sum += v
            self._count += 1

    def snapshot(self) -> Tuple[List[int], float, int]:
        """(per-bucket counts incl. +Inf, sum, count) — one consistent
        view under the lock."""
        with self._lock:
            return list(self._counts), self._sum, self._count

    def cumulative(self) -> List[Tuple[float, float]]:
        """[(le, cumulative_count)] including the +Inf bucket."""
        counts, _, _ = self.snapshot()
        out, acc = [], 0
        for u, c in zip(self.uppers, counts):
            acc += c
            out.append((u, float(acc)))
        out.append((float("inf"), float(acc + counts[-1])))
        return out

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    def quantile(self, q: float) -> float:
        return quantile_from_cumulative(self.cumulative(), q)


class Histogram(_Metric):
    """Fixed-bucket latency/size distribution with Prometheus
    cumulative-bucket exposition and host-side quantile estimation
    (linear interpolation inside the target bucket — the same estimate
    ``histogram_quantile`` computes server-side)."""

    kind = "histogram"

    def __init__(self, name, help, labelnames, label_cap,
                 buckets=DEFAULT_BUCKETS):
        uppers = tuple(sorted(float(b) for b in buckets))
        if not uppers:
            raise ValueError("histogram needs at least one bucket")
        if uppers[-1] == float("inf"):
            uppers = uppers[:-1]        # +Inf is implicit
        self.buckets = uppers
        super().__init__(name, help, labelnames, label_cap)

    def _make_child(self):
        return _HistogramChild(self._lock, self.buckets)

    def observe(self, v: float):
        self._default().observe(v)

    @property
    def count(self) -> int:
        return self._default().count

    @property
    def sum(self) -> float:
        return self._default().sum

    def quantile(self, q: float) -> float:
        return self._default().quantile(q)

    def aggregate_snapshot(self) -> Tuple[List[int], float, int]:
        """(per-bucket counts incl. +Inf, sum, count) summed across
        every child series — the label-blind view a rolling SLO window
        snapshots (children share one bucket layout by construction)."""
        counts = [0] * (len(self.buckets) + 1)
        total, n = 0.0, 0
        for _key, child in self._snapshot():
            c, s, cnt = child.snapshot()
            for i, v in enumerate(c):
                counts[i] += v
            total += s
            n += cnt
        return counts, total, n


class MetricsRegistry:
    """Named metrics in registration order.  Registration is
    idempotent: re-registering an existing name returns the existing
    metric (modules register at construction time and engines/trainers
    are built many times per process) — but a kind/label mismatch is a
    loud error, never a silent shadow."""

    def __init__(self, label_cap: Optional[int] = None):
        self._lock = threading.Lock()
        self._metrics: "collections.OrderedDict[str, _Metric]" = \
            collections.OrderedDict()  # guarded-by: self._lock
        self._label_cap = label_cap
        self.dropped_labels = self.counter(
            "vt_metrics_dropped_labels_total",
            "label assignments collapsed into the _other series by the "
            "per-metric cardinality cap (root.common.observe.label_cap)")

    def _cap(self) -> int:
        if self._label_cap is not None:
            return self._label_cap
        return int(root.common.observe.get("label_cap", 64))

    def _register(self, cls, name, help, labels, **kw) -> _Metric:
        with self._lock:
            m = self._metrics.get(name)
            if m is not None:
                if not isinstance(m, cls) or m.labelnames != tuple(labels):
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{m.kind} with labels {m.labelnames}")
                return m
            m = cls(name, help, tuple(labels), self._cap(), **kw)
            m._dropped = getattr(self, "dropped_labels", None)
            self._metrics[name] = m
            return m

    def counter(self, name: str, help: str,
                labels: Tuple[str, ...] = ()) -> Counter:
        return self._register(Counter, name, help, labels)

    def gauge(self, name: str, help: str,
              labels: Tuple[str, ...] = ()) -> Gauge:
        return self._register(Gauge, name, help, labels)

    def histogram(self, name: str, help: str,
                  labels: Tuple[str, ...] = (),
                  buckets=DEFAULT_BUCKETS) -> Histogram:
        return self._register(Histogram, name, help, labels,
                              buckets=buckets)

    def get(self, name: str) -> Optional[_Metric]:
        with self._lock:
            return self._metrics.get(name)

    def _ordered(self) -> List[_Metric]:
        with self._lock:
            return list(self._metrics.values())

    def render(self) -> str:
        """Prometheus text exposition format 0.0.4: ``# HELP`` /
        ``# TYPE`` per metric, one sample line per child series,
        histograms as cumulative ``_bucket{le=...}`` + ``_sum`` +
        ``_count``."""
        lines: List[str] = []
        for m in self._ordered():
            lines.append(f"# HELP {m.name} {_escape_help(m.help)}")
            lines.append(f"# TYPE {m.name} {m.kind}")
            for key, child in m._snapshot():
                pairs = [f'{n}="{_escape_label(v)}"'
                         for n, v in zip(m.labelnames, key)]
                if m.kind == "histogram":
                    base = ",".join(pairs)
                    acc = 0
                    counts, total, count = child.snapshot()
                    for u, c in zip(m.buckets, counts):
                        acc += c
                        lab = base + ("," if base else "") \
                            + f'le="{_fmt(u)}"'
                        lines.append(f"{m.name}_bucket{{{lab}}} {acc}")
                    lab = base + ("," if base else "") + 'le="+Inf"'
                    lines.append(
                        f"{m.name}_bucket{{{lab}}} {acc + counts[-1]}")
                    suffix = f"{{{base}}}" if base else ""
                    lines.append(f"{m.name}_sum{suffix} {_fmt(total)}")
                    lines.append(f"{m.name}_count{suffix} {count}")
                else:
                    suffix = f"{{{','.join(pairs)}}}" if pairs else ""
                    lines.append(
                        f"{m.name}{suffix} {_fmt(child.value)}")
        return "\n".join(lines) + "\n"


class HistogramWindow:
    """Time-windowed view over a cumulative histogram: a bounded ring of
    bucket snapshots taken at slice boundaries, so quantiles over "the
    last ``window_s`` seconds" come out of the same fixed buckets the
    since-boot series exposes (a cumulative histogram hides a fresh
    regression behind hours of good history — the SLO problem,
    docs/observability.md "Rolling SLO windows").

    ``source`` is a zero-arg callable returning the Histogram (or None
    before it is registered) — late binding keeps this module free of
    any registration-order coupling.  Rotation is lazy: every read (or
    an explicit :meth:`tick`) appends a snapshot once a slice elapsed,
    so a cheap ticker — the decode scheduler tick, the SLO ticker
    thread — keeps the ring honest and an idle process pays nothing.
    ``clock`` is injectable for deterministic tests."""

    def __init__(self, source, window_s: float, slices: int = 12,
                 clock=time.monotonic):
        self._source = source
        self.window_s = max(float(window_s), 1e-9)
        self.slices = max(int(slices), 1)
        self.slice_s = self.window_s / self.slices
        self._clock = clock
        self._lock = threading.Lock()
        # ring of (t, cumulative counts incl. +Inf, sum, count); one
        # extra slot keeps a baseline just outside the window
        self._ring: collections.deque = collections.deque(
            maxlen=self.slices + 1)  # guarded-by: self._lock

    def _snap(self):
        hist = self._source()
        if hist is None:
            return None, [0], 0.0, 0
        counts, total, n = hist.aggregate_snapshot()
        return hist, counts, total, n

    def tick(self) -> bool:
        """Rotate if a slice boundary passed (idempotent; the no-op
        path is one clock read + a deque peek).  Returns whether a
        snapshot was appended — callers refresh derived gauges only on
        rotation."""
        now = self._clock()
        with self._lock:
            if self._ring and now - self._ring[-1][0] < self.slice_s:
                return False
            _hist, counts, total, n = self._snap()
            self._ring.append((now, counts, total, n))
            return True

    def delta(self):
        """(histogram, cumulative ``(le, count)`` pairs, count, sum) of
        the observations inside the window: current state minus the
        newest snapshot at least ``window_s`` old (or the oldest held —
        a young ring covers less than the full window, never more)."""
        self.tick()
        now = self._clock()
        hist, counts, total, n = self._snap()
        if hist is None:
            return None, [], 0, 0.0
        base = None
        with self._lock:
            for t, c, s, cnt in self._ring:
                if base is None or t <= now - self.window_s:
                    base = (c, s, cnt)
        bc, bs, bn = base if base is not None \
            else ([0] * len(counts), 0.0, 0)
        if len(bc) != len(counts):      # ring predates the registration
            bc = [0] * len(counts)
        pairs, acc = [], 0
        for u, cur, old in zip(hist.buckets, counts, bc):
            acc += cur - old
            pairs.append((u, float(acc)))
        pairs.append((float("inf"),
                      float(acc + counts[-1] - bc[-1])))
        return hist, pairs, n - bn, total - bs

    def quantile(self, q: float) -> float:
        _hist, pairs, _n, _s = self.delta()
        return quantile_from_cumulative(pairs, q)

    def summary(self, quantiles=(0.5, 0.95, 0.99)) -> dict:
        """Windowed count / sum / quantiles in one consistent read."""
        _hist, pairs, n, s = self.delta()
        out = {"count": int(n), "sum": round(s, 6)}
        for q in quantiles:
            out[f"p{int(q * 100)}"] = quantile_from_cumulative(pairs, q)
        return out


class ScopedCounter:
    """A per-instance view over a shared registry counter series: every
    ``inc()`` feeds the process-global Prometheus series, while ``n``
    counts THIS instance's increments — so ``engine.stats()`` on a
    fresh engine still starts at zero even though the registry series
    (which outlives engines) does not reset.  ``n``'s own thread
    discipline is the caller's, exactly as it was for the plain ints
    these replace."""

    __slots__ = ("_child", "n")

    def __init__(self, child):
        self._child = child
        self.n = 0

    def inc(self, amount: int = 1):
        self.n += amount
        self._child.inc(amount)


# -- the process-global registry --------------------------------------------

_REGISTRY = MetricsRegistry()


def registry() -> MetricsRegistry:
    """THE process registry: everything ``GET /metrics`` renders."""
    return _REGISTRY


# -- span ring: request/step timelines as Chrome-trace JSON ------------------

#: monotonic origin for trace timestamps (Chrome trace ``ts`` is in
#: microseconds; an absolute epoch would overflow the viewer's slider).
_T0 = time.monotonic()

_TRACE_IDS = itertools.count(1)


def next_trace_id() -> int:
    """Process-unique track id for a request timeline (``next`` on an
    itertools.count is atomic under the GIL)."""
    return next(_TRACE_IDS)


def _us(t: float) -> float:
    return round((t - _T0) * 1e6, 1)


class SpanRing:
    """Bounded ring of completed host-side spans in Chrome trace event
    format.  Bounded because it lives for the process: a serving day at
    qps keeps only the most recent ``capacity`` spans, which is exactly
    the window an operator pulls when something is slow NOW."""

    def __init__(self, capacity: int = 512):
        self._lock = threading.Lock()
        self._events: collections.deque = collections.deque(
            maxlen=max(1, int(capacity)))  # guarded-by: self._lock

    def add(self, name: str, start_s: float, dur_s: float, *,
            cat: str = "host", tid: int = 0, args: Optional[dict] = None):
        """One complete ("X") span: ``start_s``/``dur_s`` in
        ``time.monotonic()`` seconds."""
        ev = {"name": str(name), "cat": cat, "ph": "X",
              "ts": _us(start_s), "dur": round(max(dur_s, 0.0) * 1e6, 1),
              "pid": 0, "tid": int(tid)}
        if args:
            ev["args"] = args
        with self._lock:
            self._events.append(ev)

    def add_instant(self, name: str, at_s: float, *, cat: str = "event",
                    tid: int = 0, args: Optional[dict] = None):
        ev = {"name": str(name), "cat": cat, "ph": "i", "s": "g",
              "ts": _us(at_s), "pid": 0, "tid": int(tid)}
        if args:
            ev["args"] = args
        with self._lock:
            self._events.append(ev)

    def __len__(self) -> int:
        with self._lock:
            return len(self._events)

    def snapshot(self) -> List[dict]:
        with self._lock:
            return sorted(self._events, key=lambda e: e["ts"])

    def chrome_trace(self) -> dict:
        """The ``chrome://tracing`` / Perfetto document (also the
        ``GET /trace.json`` body)."""
        meta = [{"name": "process_name", "ph": "M", "pid": 0, "tid": 0,
                 "args": {"name": "veles_tpu"}}]
        return {"traceEvents": meta + self.snapshot(),
                "displayTimeUnit": "ms"}


_SPANS_LOCK = threading.Lock()
_SPANS: Optional[SpanRing] = None  # guarded-by: _SPANS_LOCK


def span_ring() -> SpanRing:
    """The process span ring, sized by ``root.common.observe.span_ring``
    at first use."""
    global _SPANS
    with _SPANS_LOCK:
        if _SPANS is None:
            _SPANS = SpanRing(
                int(root.common.observe.get("span_ring", 512)))
        return _SPANS


def write_chrome_trace(path: str) -> str:
    """``--trace-out FILE``: dump the current span ring as Chrome-trace
    JSON (open in Perfetto: ui.perfetto.dev → Open trace file)."""
    with open(path, "w") as f:
        json.dump(span_ring().chrome_trace(), f, default=repr)
    return path


# -- scrape-side helpers (bench_serving.py, tests) ---------------------------

_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{(.*)\})?\s+(\S+)$")
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')
_UNESCAPE_RE = re.compile(r"\\(.)")
_UNESCAPES = {"n": "\n", '"': '"', "\\": "\\"}


def _unescape_label(s: str) -> str:
    """Single-pass inverse of :func:`_escape_label` — sequential
    ``str.replace`` calls would corrupt a value holding a literal
    backslash before an 'n' (``\\\\n`` is backslash+n, not newline)."""
    return _UNESCAPE_RE.sub(
        lambda m: _UNESCAPES.get(m.group(1), "\\" + m.group(1)), s)


def parse_samples(text: str) -> List[Tuple[str, Dict[str, str], float]]:
    """Parse Prometheus exposition text into ``(name, labels, value)``
    sample tuples — the scrape half the bench uses to turn a
    ``/metrics`` body back into numbers."""
    out = []
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if not m:
            continue
        name, raw_labels, raw_v = m.groups()
        labels = {}
        for k, v in _LABEL_RE.findall(raw_labels or ""):
            labels[k] = _unescape_label(v)
        try:
            out.append((name, labels, float(raw_v)))
        except ValueError:
            continue
    return out


def cumulative_buckets(samples, name: str) -> List[Tuple[float, float]]:
    """Aggregate a histogram's ``_bucket`` samples (summing across any
    non-``le`` labels) into sorted ``[(le, cumulative_count)]``."""
    agg: Dict[float, float] = {}
    for n, labels, v in samples:
        if n != name + "_bucket" or "le" not in labels:
            continue
        le = float(labels["le"])
        agg[le] = agg.get(le, 0.0) + v
    return sorted(agg.items())


def delta_buckets(before, after) -> List[Tuple[float, float]]:
    """Cumulative-bucket difference of two scrapes — how a bench
    isolates one scenario's distribution on the process-global
    registry."""
    base = dict(before)
    return [(le, c - base.get(le, 0.0)) for le, c in after]


def fraction_over(pairs, threshold: float) -> float:
    """Fraction of observations above ``threshold`` from cumulative
    ``(le, count)`` pairs, interpolating linearly inside the bucket the
    threshold lands in (the same estimate the quantile helper inverts)
    — the burn-rate numerator of the rolling SLO windows."""
    pairs = sorted(pairs)
    if not pairs or pairs[-1][1] <= 0:
        return 0.0
    total = pairs[-1][1]
    prev_le, prev_c = 0.0, 0.0
    for le, c in pairs:
        if threshold <= le:
            if le == float("inf"):
                return (total - prev_c) / total
            width = le - prev_le
            frac = (threshold - prev_le) / width if width > 0 else 1.0
            at = prev_c + frac * (c - prev_c)
            return max(0.0, (total - at) / total)
        prev_le, prev_c = le, c
    return 0.0


def quantile_from_cumulative(pairs, q: float) -> float:
    """Quantile estimate from cumulative ``(le, count)`` pairs: linear
    interpolation inside the target bucket, the last finite bound for
    the +Inf bucket (Prometheus ``histogram_quantile`` semantics)."""
    pairs = sorted(pairs)
    if not pairs or pairs[-1][1] <= 0:
        return 0.0
    q = min(max(float(q), 0.0), 1.0)
    target = q * pairs[-1][1]
    prev_le, prev_c = 0.0, 0.0
    for le, c in pairs:
        if c >= target:
            if le == float("inf"):
                return prev_le
            width_c = c - prev_c
            frac = (target - prev_c) / width_c if width_c > 0 else 1.0
            return prev_le + frac * (le - prev_le)
        if le != float("inf"):
            prev_le = le
        prev_c = c
    return prev_le
