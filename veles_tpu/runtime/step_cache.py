"""StepCache: ahead-of-time step compilation cache + persistent XLA cache.

On TPU a sharded (or 1F1B-pipelined) train-step compile is the single most
expensive host-side event in a run — tens of seconds for a real model.  The
rebuild's two-program design (SURVEY.md §7) makes the program set small and
static, so the lifecycle goal is simple: **compile each program exactly
once per workflow lifetime**, and never again on a Decision rollback, a
``Trainer.restore``, or a re-``initialize`` with unchanged shapes.

Three layers:

* the traced lr multiplier (``ops.optimizers.LR_MULT_KEY``) removes the
  only *semantic* reason the Trainer ever re-traced a step;
* this in-process cache AOT-compiles each step via ``.lower().compile()``
  and keys it on everything that determines the traced program — the
  workflow instance (pinned), its graph checksum, the state/batch
  structures, mesh axes + devices, sharding-rule identity, optimizer
  configuration, and the pipeline schedule knobs.  Its counters
  (``compiles`` / ``hits`` / ``recompiles``) are the observable contract
  tests assert on;
* JAX's persistent compilation cache (:func:`enable_persistent_cache`,
  ``root.common.compile_cache`` / ``--compile-cache``) carries compiled
  executables ACROSS processes, keyed on the HLO — a restarted run with
  an unchanged program skips XLA entirely.

Per-program cost analysis (FLOPs, bytes accessed, compile wall seconds)
is logged through the existing :class:`~veles_tpu.logger.TraceContext` /
event-trace path, so ``root.common.trace_file`` timelines show compile
cost next to step cost.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, Optional, Tuple

import jax

from ..config import root
from ..logger import Logger, TraceContext
from .metrics import registry


def enable_persistent_cache(cache_dir: Optional[str] = None) -> bool:
    """Point JAX's persistent compilation cache at ``cache_dir`` (default:
    ``root.common.compile_cache``; empty = disabled).  Idempotent, safe to
    call before every compile; returns whether the cache is active.

    The persistent cache is keyed on the optimized HLO + compile options,
    so it composes with (rather than replaces) the in-process StepCache:
    a process restart re-traces but skips the XLA backend compile.
    """
    import os
    cache_dir = cache_dir if cache_dir is not None \
        else root.common.get("compile_cache", "")
    if not cache_dir:
        return False
    cache_dir = os.path.abspath(os.path.expanduser(str(cache_dir)))
    os.makedirs(cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    # default min-compile-time gate (1s) would silently skip the small
    # CPU-tier programs the tests exercise; cache everything unless the
    # config says otherwise
    min_secs = float(root.common.get("compile_cache_min_compile_secs", 0.0))
    try:
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs", min_secs)
    except (AttributeError, ValueError):  # older jax without the knob
        pass
    # jax initializes its cache object at most ONCE, at the first backend
    # compile — a loader/prng jit before this call would freeze it to
    # "no directory" forever; reset to pristine when the live cache does
    # not point at the requested directory so the update takes effect.
    try:
        from jax._src import compilation_cache as _cc
        live = getattr(_cc, "_cache", None)
        # _path is a pathlib-style object — compare as str, else the
        # mismatch guard is always true and every call resets
        live_path = str(getattr(live, "_path", "")) if live is not None \
            else None
        if getattr(_cc, "_cache_initialized", False) \
                and live_path != cache_dir:
            _cc.reset_cache()
    except Exception:
        pass
    return True


def _leaf_sig(path, leaf) -> Tuple[str, str, str]:
    return (jax.tree_util.keystr(path),
            str(getattr(leaf, "shape", ())),
            str(getattr(leaf, "dtype", type(leaf).__name__)))


def tree_signature(tree) -> Tuple:
    """Hashable (path, shape, dtype) signature of a pytree of arrays or
    ShapeDtypeStructs — the part of a step's identity its checksum does
    not cover (layer widths, optimizer slot layout, batch geometry)."""
    return tuple(_leaf_sig(p, l) for p, l in
                 jax.tree_util.tree_leaves_with_path(tree))


def _optimizer_signature(optimizer) -> Tuple:
    """Scalar hyperparameters by value + schedule IDENTITY.  The schedule
    is an opaque closure baked into the trace, so it can only be compared
    by object identity — a rebuilt optimizer therefore always misses even
    with identical settings (conservative: a stale hit would silently
    train with the wrong lr curve).  The scalars still matter: they make
    a mutated optimizer on the SAME schedule object miss."""
    scalars = tuple(sorted(
        (k, v) for k, v in vars(optimizer).items()
        if isinstance(v, (int, float, bool, str))))
    per_unit = getattr(optimizer, "per_unit", None)
    return (type(optimizer).__name__, scalars,
            id(getattr(optimizer, "schedule", None)),
            repr(sorted(per_unit.items())) if per_unit else None)


class StepCache(Logger):
    """Process-level cache of AOT-compiled step executables.

    ``get_step(kind, key, builder, args)`` returns the cached
    ``(step_fn, state_shardings, batch_shardings)`` for ``(kind, key)``
    or invokes ``builder`` once, lowers the jitted function against the
    argument ShapeDtypeStructs, compiles it, logs its cost analysis, and
    caches the executable.  ``builder`` must return the
    ``(jitted_fn, state_sh, batch_sh)`` triple of the Workflow ``make_*``
    contract (state_sh/batch_sh may be None off-mesh).

    Counters: ``compiles`` is the number of trace+compile events ever,
    ``hits`` the number served from cache, ``recompiles`` the compiles
    beyond one per distinct program — the quantity the recompile-free
    lifecycle keeps at zero across rollbacks and restores.
    """

    def __init__(self, *, aot: bool = True, strict: bool = False):
        self.aot = aot
        # strict: an AOT lower/compile failure RAISES instead of
        # falling back to on-demand jit.  The lazy fallback is a valid
        # degradation for freshly traced model code (exotic signatures
        # still run); for a sealed artifact's deserialized programs it
        # would turn a load-time failure into a mid-request crash.
        self.strict = strict
        self._entries: Dict[Any, dict] = {}
        self.compiles = 0
        self.hits = 0
        self.compile_wall_s = 0.0
        # process-global compile series next to the per-cache counters
        # (runtime/metrics.py): /metrics shows compiles across EVERY
        # cache in the process, so "flat under load" is checkable from
        # one scrape while stats() keeps the per-cache contract
        reg = registry()
        self._m_compiles = reg.counter(
            "vt_compile_total",
            "trace+compile events by program kind (train / eval / "
            "decode / prefill / verify) across every StepCache in the "
            "process", labels=("program",))
        self._m_hits = reg.counter(
            "vt_compile_hits_total",
            "step programs served from cache", labels=("program",))
        self._m_wall = reg.counter(
            "vt_compile_wall_seconds_total",
            "wall seconds spent tracing+compiling step programs")
        # per-program-kind cost analysis (the goodput/MFU numerators,
        # docs/observability.md "Goodput & MFU"): gauges because the
        # inventory is a point-in-time fact of the newest cache to
        # compile that kind, not a monotone event count
        self._g_flops = reg.gauge(
            "vt_program_flops",
            "XLA cost-analysis flops per execution, summed over the "
            "compiled programs of a kind (prefill sums its buckets)",
            labels=("program",))
        self._g_bytes = reg.gauge(
            "vt_program_bytes_accessed",
            "XLA cost-analysis bytes accessed per execution, summed "
            "over the compiled programs of a kind", labels=("program",))

    @property
    def recompiles(self) -> int:
        return self.compiles - len(self._entries)

    # -- keys ---------------------------------------------------------------
    def trainer_key(self, workflow, optimizer, wstate, batch_spec, *,
                    mesh=None, rule=None, pipeline: Tuple = ()) -> Tuple:
        """Cache key for a Trainer's step programs.

        The workflow INSTANCE anchors the key (unit hyperparameters like
        dropout ratios live on unit objects and are invisible to both the
        topology checksum and the state signature); the entry pins a
        strong reference so ``id`` stays unique while cached.  The
        structural components make shape/mesh/optimizer changes miss
        instead of serving a stale executable.
        """
        mesh_sig = None
        if mesh is not None:
            mesh_sig = (tuple(mesh.shape.items()),
                        tuple(d.id for d in mesh.devices.flat))
        return (id(workflow), workflow.checksum(),
                tree_signature(wstate), tree_signature(batch_spec),
                mesh_sig, id(rule) if rule is not None else None,
                _optimizer_signature(optimizer), tuple(pipeline))

    # -- the cache ----------------------------------------------------------
    def get_step(self, kind: str, key: Tuple,
                 builder: Callable[[], Tuple], args: Tuple, *,
                 pin: Tuple = ()) -> Tuple:
        """Fetch or build+AOT-compile the ``kind`` ('train'/'eval') step."""
        full_key = (kind,) + tuple(key)
        ent = self._entries.get(full_key)
        if ent is not None:
            self.hits += 1
            self._m_hits.labels(program=kind).inc()
            return ent["fn"], ent["state_sh"], ent["batch_sh"]

        with TraceContext("step_compile", program=kind):
            t0 = time.perf_counter()
            fn, state_sh, batch_sh = builder()
            compiled = None
            if self.aot:
                try:
                    compiled = fn.lower(*args).compile()
                except Exception as e:  # exotic signature: keep the jit
                    if self.strict:
                        raise
                    self.warning(
                        "AOT compile of %s step failed (%s: %s); falling "
                        "back to on-demand jit", kind, type(e).__name__, e)
            wall = time.perf_counter() - t0
        self.compiles += 1
        self.compile_wall_s += wall
        self._m_compiles.labels(program=kind).inc()
        self._m_wall.inc(wall)

        cost: Dict[str, float] = {}
        if compiled is not None:
            try:
                ca = compiled.cost_analysis()
                if isinstance(ca, (list, tuple)):
                    ca = ca[0] if ca else {}
                for label, k in (("flops", "flops"),
                                 ("bytes_accessed", "bytes accessed")):
                    if k in ca:
                        cost[label] = float(ca[k])
            except Exception:  # cost analysis is best-effort observability
                pass
        self.event("step_compile", program=kind, wall_s=round(wall, 4),
                   **cost)
        self.info(
            "compiled %s step in %.2fs (%.3g GFLOP/step, %.3g MB/step)",
            kind, wall, cost.get("flops", 0.0) / 1e9,
            cost.get("bytes_accessed", 0.0) / 1e6)
        self._entries[full_key] = {
            "fn": compiled if compiled is not None else fn,
            "state_sh": state_sh, "batch_sh": batch_sh,
            "wall_s": wall, "cost": cost,
            # strong refs keep id()-anchored key components unique for
            # the cache's lifetime (id reuse after GC would alias keys)
            "pin": pin,
        }
        kc = self.program_cost(kind)
        self._g_flops.labels(program=kind).set(kc["flops"])
        self._g_bytes.labels(program=kind).set(kc["bytes_accessed"])
        return (self._entries[full_key]["fn"], state_sh, batch_sh)

    def entry_cost(self, kind: str, key: Tuple) -> Dict[str, float]:
        """Cost analysis of ONE cached program — the entry the caller
        actually executes.  Use this (not :meth:`program_cost`) when the
        cache can hold superseded programs of the same kind: a Trainer
        whose optimizer was rebuilt keeps the old train entry forever
        (conservative cache policy), and summing both would double the
        reported flops — on the very metric meant as the honesty
        check."""
        ent = self._entries.get((kind,) + tuple(key))
        if ent is None:
            return {"flops": 0.0, "bytes_accessed": 0.0}
        return {"flops": float(ent["cost"].get("flops", 0.0)),
                "bytes_accessed":
                    float(ent["cost"].get("bytes_accessed", 0.0))}

    def program_cost(self, kind: str) -> Dict[str, float]:
        """Summed cost analysis of this cache's compiled ``kind``
        programs: ``{"flops", "bytes_accessed"}`` per execution (zeros
        when XLA reported nothing — consumers treat 0 as unknown).
        Correct when every entry of the kind is live inventory (an
        engine's prefill buckets + its one decode step); see
        :meth:`entry_cost` for the superseded-entries caveat."""
        flops = bytes_acc = 0.0
        for full_key, ent in self._entries.items():
            if full_key[0] != kind:
                continue
            flops += float(ent["cost"].get("flops", 0.0))
            bytes_acc += float(ent["cost"].get("bytes_accessed", 0.0))
        return {"flops": flops, "bytes_accessed": bytes_acc}

    def stats(self) -> Dict[str, Any]:
        """JSON-able summary for benchmarks and status pages."""
        return {"programs": len(self._entries), "compiles": self.compiles,
                "hits": self.hits, "recompiles": self.recompiles,
                "compile_wall_s": round(self.compile_wall_s, 3)}
