"""Per-device op-variant autotuning with a persisted winner DB.

Reference parity: veles/backends.py:672-731 — the OpenCL backend swept
gemm block sizes (3 reps, size 3001) per device and persisted the winner
to ``devices/device_infos.json``, reused on every later run. Generalized
here for the TPU build: any op with several mathematically-equivalent
formulations (LRN band-matmul vs cumsum-difference, Pallas kernel vs XLA
expression, ...) asks :func:`pick` for the measured winner on THIS device
for THIS shape class; winners persist under the ``autotune`` key of the
same per-device-kind DB the gemm benchmark uses
(``runtime/benchmark.py``).

Measurement methodology matches ``bench_tpu.py``: repetitions are chained
INSIDE one jit with an ``optimization_barrier`` and a denormal feedback
term, so per-dispatch tunnel latency is amortized and XLA can neither
fold repetitions nor skip materializing outputs (the round-2 harness bug
that mis-decided two kernel defaults — BASELINE.md).
"""

from __future__ import annotations

import time
from typing import Callable, Dict, Mapping, Optional, Sequence

from ..config import root
from ..logger import Logger
from .benchmark import (device_info_path, load_device_infos,
                        update_device_info)

class _AutotuneLog(Logger):
    pass


_log = _AutotuneLog()

# In-process memo so one run never re-reads the DB (or re-measures) for
# the same decision twice.
_memo: Dict[str, str] = {}


def _shape_key(args: Sequence) -> str:
    parts = []
    for a in args:
        shape = tuple(getattr(a, "shape", ()) or ())
        dtype = getattr(a, "dtype", None)
        parts.append(f"{'x'.join(map(str, shape))}:{dtype}")
    return ",".join(parts)


def measure(fn: Callable, args: Sequence, reps: int = 4,
            iters: int = 3) -> float:
    """Per-call seconds for fn(*args), reps chained in-graph."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    # Chain the inter-rep data dependence through the SMALLEST argument
    # so the chain edge itself is nearly free (threading it through a
    # large operand would add a full HBM pass per repetition —
    # bench_tpu.py's harness note).
    j = int(np.argmin([int(np.prod(getattr(a, "shape", ()) or (1,)))
                       for a in args]))

    def chained(*args):
        out = fn(*args)
        for _ in range(reps - 1):
            out = jax.lax.optimization_barrier(out)
            leaf = jax.tree.leaves(out)[0]
            eps = jnp.sum(leaf.astype(jnp.float32)) * 1e-38
            args = list(args)
            args[j] = args[j] + eps.astype(args[j].dtype)
            out = fn(*args)
        return out

    cf = jax.jit(chained)
    out = cf(*args)
    # scalar read drains the queue (block_until_ready is unreliable over
    # the axon tunnel — bench.py)
    float(jnp.sum(jax.tree.leaves(out)[0].astype(jnp.float32)))
    t0 = time.perf_counter()
    for _ in range(iters):
        out = cf(*args)
    float(jnp.sum(jax.tree.leaves(out)[0].astype(jnp.float32)))
    return (time.perf_counter() - t0) / (iters * reps)


def lookup(op: str, names: Sequence[str], args: Sequence,
           cache_dir: Optional[str] = None) -> Optional[str]:
    """Winner for ``op`` from memo/DB only — never measures. Returns
    None when no valid record for this candidate set exists. Lets
    callers skip building measurement inputs entirely on warm starts
    (e.g. the loader's sample pack)."""
    import jax

    kind = jax.devices()[0].device_kind
    key = f"{op}|{_shape_key(args)}"
    memo_key = f"{device_info_path(cache_dir)}|{kind}|{key}"
    if memo_key in _memo and _memo[memo_key] in names:
        return _memo[memo_key]
    try:
        infos = load_device_infos(cache_dir)
    except Exception:
        return None
    rec = infos.get(kind, {}).get("autotune", {}).get(key)
    if (rec and rec.get("winner") in names
            and set(rec.get("ms", ())) == set(names)):
        _memo[memo_key] = rec["winner"]
        return rec["winner"]
    return None


def pick(op: str, candidates: Mapping[str, Callable], args: Sequence,
         default: Optional[str] = None, cache_dir: Optional[str] = None,
         refresh: bool = False) -> str:
    """Name of the fastest candidate for ``op`` on the current device.

    Measured at most once per (device kind, op, arg shapes/dtypes);
    afterwards answered from the in-process memo or the persisted DB.
    On any measurement failure returns ``default`` (or the first
    candidate) — autotuning must never break the build.
    """
    import jax

    names = list(candidates)
    if default is None:
        default = names[0]
    if len(names) == 1:
        return names[0]
    if not bool(root.common.autotune):
        return default

    kind = jax.devices()[0].device_kind
    key = f"{op}|{_shape_key(args)}"
    # cache_dir in the memo key: callers mixing explicit and default DBs
    # must not receive each other's winners
    memo_key = f"{device_info_path(cache_dir)}|{kind}|{key}"
    if not refresh:
        cached = lookup(op, names, args, cache_dir)
        if cached is not None:
            return cached

    timings = {}
    try:
        for name in names:
            timings[name] = measure(candidates[name], args)
    except Exception as e:
        _log.warning("autotune %s failed (%s: %s); using default %r",
                     op, type(e).__name__, e, default)
        _memo[memo_key] = default
        return default

    winner = min(timings, key=timings.get)
    _log.info("autotune %s on %s: %s  -> %s", op, kind,
              {k: f"{v * 1e3:.3f}ms" for k, v in timings.items()}, winner)
    record = {"winner": winner,
              "ms": {k: round(v * 1e3, 4) for k, v in timings.items()}}
    try:
        update_device_info(
            kind, lambda rec: rec.setdefault("autotune", {})
            .__setitem__(key, record), cache_dir)
    except OSError as e:  # read-only cwd etc. — the memo still holds
        _log.warning("autotune DB not persisted: %s", e)
    _memo[memo_key] = winner
    return winner
