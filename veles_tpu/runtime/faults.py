"""Deterministic, config-driven fault injection for the training and
serving lifecycle (docs/robustness.md).

The reference got its chaos testing for free: a slave process could be
killed at any point and the master re-served its minibatches from owned
state (veles/server.py:315-338, veles/loader/base.py:679-687).  The
SPMD rebuild's recovery unit is the whole process (tests/test_chaos.py),
so the failure modes worth rehearsing are the ones that *don't* kill the
process: a NaN loss, a torn snapshot write, a flaky storage read, a dead
scheduler thread.  This module is the one switchboard those rehearsals
go through — production code consults it at well-defined injection
points, tests arm it through ``root.common.faults.*`` (or the
:func:`configure` convenience) and get bit-deterministic failures.

Knobs (all off by default; ``root.common.faults`` stays an empty config
node in production, so the :func:`enabled` fast path is one falsy check):

``nan_grad_at_step``
    int or list of ints.  Poison every gradient leaf with NaN at these
    global step numbers.  Injected IN-GRAPH at trace time (a traced
    compare against ``wstate["step"]``), so the injection adds ZERO
    recompiles — the property the anomaly sentinel's own tests assert.
    Arm it BEFORE the step compiles (``Trainer.initialize``); arming
    later hits the already-cached executable.
``loader_ioerror_at_batch``
    int or list of ints.  The FIRST fetch attempt of these batch
    indices raises ``OSError`` (once per index per process), so the
    loader's bounded retry recovers — the Veles failed-minibatch-requeue
    analog.
``truncate_snapshot``
    truthy.  Every ``Snapshotter.save`` truncates its tensors blob to
    half size AFTER the atomic symlink flip — a torn write discovered
    only at restore time (exercises checksum verify + walk-back).
``slow_batch_ms``
    float.  Sleep this many milliseconds inside every batch fetch
    (prefetch/backpressure rehearsal).
``scheduler_crash``
    truthy.  The decode-engine scheduler loop raises
    :class:`FaultInjected` (once) at its next iteration with pending
    work — exercises the fail-all-loudly crash path.
``decode_stall_ms``
    float.  The next decode step sleeps this long before dispatching
    (once per arming) — one artificially slow step, the SLO-burn /
    tail-latency rehearsal the admission controller's tests drive
    (docs/serving.md "Overload survival").
``admission_burst``
    int.  The decode-engine scheduler injects this many synthetic
    minimal lowest-priority requests straight into its own queue (once
    per arming) — a queue flood that deliberately bypasses ``submit``'s
    shed gate, because the rehearsal is "the backlog already exists;
    prove the controller sheds and then re-opens"
    (tests/test_chaos.py).
``replica_crash_at_request``
    int.  The fleet router (runtime/fleet.py) kills the replica it
    chose for its Nth dispatched request — once per arming, and only
    replicas the router owns a kill handle for (in-process /
    subprocess children; URL-joined replicas have no handle).  The
    dispatch then fails over: ejection, idempotent resubmission to a
    survivor, zero class-0 failures (tests/test_chaos.py fleet
    rehearsal).
``replica_slow_ms``
    float.  Every router dispatch to the LOWEST-ID active replica is
    held back this many milliseconds (a persistently slow replica as
    seen from the router): its outstanding count grows and the
    load-affinity dispatch shifts traffic to the fast survivors.
    Fires per request while armed, like ``slow_batch_ms``.
``kv_transfer_drop``
    int.  The fleet router's first N KV-page transfers (remote prefix
    fetch, disagg prefill ship, drain pre-warm — runtime/fleet.py
    ``_transfer_pages``) fail as transport errors before any bytes
    move.  The request they were placed for must complete via local
    prefill — the transfer path is an optimization, never a
    dependency (tests/test_chaos.py).
``kv_transfer_slow_ms``
    float.  Every router KV-page transfer sleeps this many
    milliseconds first (a slow inter-replica link): the measured
    bandwidth EWMA degrades and the fetch-vs-reprefill payoff policy
    starts choosing local prefill on its own.
``stream_cut_at_token``
    int.  The fleet router's streaming relay
    (runtime/fleet.py ``handle_generate_stream``) severs the replica
    leg — as a transport failure — after relaying this many token
    frames (once per arming).  The relay must resume the SUFFIX on a
    survivor from the recorded high-water mark: the client sees one
    gapless, duplicate-free stream (tests/test_chaos.py streaming
    rehearsal).
``stream_stall_ms``
    float.  The streaming relay sleeps this many milliseconds per
    relayed frame (a slow consumer as seen from the replica): the
    engine-side stream handle buffers up to
    ``serve.stream.buffer_tokens`` and then terminates the stream
    with a loud overflow error instead of growing without bound.
``trial_crash_at_step``
    int.  The experiment manager (experiments/manager.py) raises
    :class:`FaultInjected` as its Nth launched trial begins training —
    once per arming, counted across the manager's process lifetime,
    AFTER the trial claimed its ledger entry and BEFORE any result
    commit.  The manager deliberately re-raises it past its own
    failure handling (a simulated process death, not a failed trial):
    the experiment stays ``running`` on disk and a fresh manager must
    resume it mid-generation — completed trials never re-run, the
    killed trial restarts from its deterministic seed
    (tests/test_chaos.py experiment rehearsal).
"""

from __future__ import annotations

import threading
from typing import Optional, Tuple

from ..config import root


class FaultInjected(RuntimeError):
    """Raised at a crash point armed via ``root.common.faults``."""


#: one-shot firing memory: (kind, index) pairs that already fired.
#: ``_lock`` also serializes arm/disarm (configure/reset) against the
#: scheduler threads consulting the plan mid-run.
_fired: set = set()  # guarded-by: _lock
_lock = threading.Lock()


def _as_steps(v) -> Tuple[int, ...]:
    """Normalize an int / float / iterable knob to a sorted int tuple."""
    if v is None or v is False or v == "":
        return ()
    if isinstance(v, bool):  # True alone names no step
        return ()
    if isinstance(v, (int, float)):
        return (int(v),)
    return tuple(sorted(int(x) for x in v))


class FaultPlan:
    """Immutable snapshot of the armed injection points."""

    __slots__ = ("nan_grad_at_step", "loader_ioerror_at_batch",
                 "truncate_snapshot", "slow_batch_ms", "scheduler_crash",
                 "decode_stall_ms", "admission_burst",
                 "replica_crash_at_request", "replica_slow_ms",
                 "kv_transfer_drop", "kv_transfer_slow_ms",
                 "stream_cut_at_token", "stream_stall_ms",
                 "trial_crash_at_step")

    def __init__(self, cfg):
        get = cfg.get
        self.nan_grad_at_step = _as_steps(get("nan_grad_at_step"))
        self.loader_ioerror_at_batch = _as_steps(
            get("loader_ioerror_at_batch"))
        self.truncate_snapshot = bool(get("truncate_snapshot", False))
        self.slow_batch_ms = float(get("slow_batch_ms", 0.0) or 0.0)
        self.scheduler_crash = bool(get("scheduler_crash", False))
        self.decode_stall_ms = float(get("decode_stall_ms", 0.0) or 0.0)
        self.admission_burst = int(get("admission_burst", 0) or 0)
        self.replica_crash_at_request = int(
            get("replica_crash_at_request", 0) or 0)
        self.replica_slow_ms = float(get("replica_slow_ms", 0.0) or 0.0)
        self.kv_transfer_drop = int(get("kv_transfer_drop", 0) or 0)
        self.kv_transfer_slow_ms = float(
            get("kv_transfer_slow_ms", 0.0) or 0.0)
        self.stream_cut_at_token = int(
            get("stream_cut_at_token", 0) or 0)
        self.stream_stall_ms = float(get("stream_stall_ms", 0.0) or 0.0)
        self.trial_crash_at_step = int(
            get("trial_crash_at_step", 0) or 0)

    def __bool__(self) -> bool:
        return bool(self.nan_grad_at_step or self.loader_ioerror_at_batch
                    or self.truncate_snapshot or self.slow_batch_ms
                    or self.scheduler_crash or self.decode_stall_ms
                    or self.admission_burst
                    or self.replica_crash_at_request
                    or self.replica_slow_ms
                    or self.kv_transfer_drop
                    or self.kv_transfer_slow_ms
                    or self.stream_cut_at_token
                    or self.stream_stall_ms
                    or self.trial_crash_at_step)

    def __repr__(self) -> str:
        armed = {k: getattr(self, k) for k in self.__slots__
                 if getattr(self, k)}
        return f"FaultPlan({armed})"


def enabled() -> bool:
    """Cheap is-anything-armed check for hot loops: an empty (or never
    touched) ``root.common.faults`` node is falsy."""
    return bool(root.common.faults)


def get_plan() -> FaultPlan:
    """Build the current plan from ``root.common.faults``.  Cheap enough
    to call per batch; injection points on compile-hot paths read it once
    at trace/build time instead."""
    return FaultPlan(root.common.faults)


def fire_once(kind: str, index: Optional[int] = None) -> bool:
    """True exactly once per (kind, index) for the process lifetime
    (until :func:`reset`) — injected transients must be recoverable by a
    bounded retry, and injected crashes must not re-kill the replacement."""
    key = (kind, index)
    with _lock:
        if key in _fired:
            return False
        _fired.add(key)
        return True


def _disarm_locked() -> None:  # requires-lock: _lock
    _fired.clear()
    node = root.common.faults
    for k in list(node.keys()):
        delattr(node, k)


def configure(**knobs) -> FaultPlan:
    """Arm injection points programmatically (test convenience): clears
    any previous plan AND the one-shot firing memory, then writes each
    knob into ``root.common.faults`` — all under the firing lock, so a
    scheduler thread can never observe a half-armed plan with the OLD
    one-shot memory (the fire-once check-then-act the concurrency
    audit flagged: a crash knob could fire twice, or never, across a
    re-configure)."""
    with _lock:
        _disarm_locked()
        for k, v in knobs.items():
            setattr(root.common.faults, k, v)
    return get_plan()


def reset() -> None:
    """Disarm everything and forget what already fired (atomic with
    respect to :func:`fire_once`)."""
    with _lock:
        _disarm_locked()
