"""HTTP client for one fleet replica (runtime/fleet.py).

A replica is an ordinary serving stack — :class:`~.restful.RestfulServer`
over a :class:`~.engine.DecodeEngine` or
:class:`~.artifact.ArtifactRunner`, with a
:class:`~.deploy.DeployController` attached — reached over plain HTTP.
The router never links against replica objects: everything it knows
about a replica flows through this client (scraped ``/engine`` stats,
``/metrics`` text, ``/ready``, dispatched ``/generate`` calls, the
two-phase ``/admin/stage`` → ``/admin/commit`` swap protocol), which is
what makes in-process replicas, subprocess children and ``--join``ed
remote processes indistinguishable to the dispatch logic.

Connection-level failures raise :class:`ReplicaUnavailable` — the
router's ejection/failover signal.  HTTP error *statuses* are returned,
not raised: a 429 is backpressure to honor, a 503 a drain to route
around, and only the router knows which of those mean "try a survivor".
No retries happen here; the router owns failover, and its health probes
wrap these calls in the ``deploy.http_retry`` backoff themselves.
"""

from __future__ import annotations

import http.client
import json
import urllib.error
import urllib.request
from typing import Optional, Tuple


class ReplicaUnavailable(RuntimeError):
    """The replica could not be reached at the transport level
    (connection refused/reset, DNS, timeout) — as opposed to an HTTP
    error status, which means the replica is alive and answering."""


class ReplicaClient:
    """Thin JSON-over-HTTP client bound to one replica base URL."""

    def __init__(self, base_url: str, *, timeout_s: float = 30.0):
        self.base_url = base_url.rstrip("/")
        self.timeout_s = float(timeout_s)

    def __repr__(self):
        return f"ReplicaClient({self.base_url})"

    # -- transport ----------------------------------------------------------
    def request(self, method: str, path: str, body: Optional[dict] = None,
                timeout: Optional[float] = None
                ) -> Tuple[int, dict, object]:
        """One HTTP exchange → ``(status, headers, parsed body)``.
        Bodies are parsed as JSON when they look like it, else returned
        as text (``/metrics``).  4xx/5xx come back as statuses with
        their parsed bodies; only transport failures raise."""
        data = None
        headers = {}
        if body is not None:
            data = json.dumps(body).encode()
            headers["Content-Type"] = "application/json"
        req = urllib.request.Request(
            self.base_url + path, data=data, headers=headers,
            method=method)
        timeout = self.timeout_s if timeout is None else float(timeout)
        try:
            with urllib.request.urlopen(req, timeout=timeout) as r:
                return r.status, dict(r.headers), self._parse(r.read())
        except urllib.error.HTTPError as e:
            # the server ANSWERED: an error status is information, not
            # unavailability — read the body before the handle closes
            with e:
                return e.code, dict(e.headers), self._parse(e.read())
        except (urllib.error.URLError, ConnectionError, TimeoutError,
                OSError) as e:
            raise ReplicaUnavailable(
                f"{self.base_url}: {type(e).__name__}: {e}") from e

    @staticmethod
    def _parse(raw: bytes):
        text = raw.decode("utf-8", "replace")
        stripped = text.lstrip()
        if stripped.startswith(("{", "[")):
            try:
                return json.loads(text)
            except json.JSONDecodeError:
                pass
        return text

    # -- scrape surface ------------------------------------------------------
    def ready(self, timeout: Optional[float] = None) -> bool:
        """``GET /ready`` → True on 200 (draining / SLO-degraded
        replicas answer 503, which reads as ``False`` — deprioritized,
        not ejected)."""
        status, _h, _b = self.request("GET", "/ready", timeout=timeout)
        return status == 200

    def engine_stats(self, timeout: Optional[float] = None
                     ) -> Optional[dict]:
        """``GET /engine`` — the per-engine load/admission snapshot the
        dispatch score reads; None when the replica serves no engine."""
        status, _h, body = self.request("GET", "/engine",
                                        timeout=timeout)
        return body if status == 200 and isinstance(body, dict) else None

    def metrics_text(self, timeout: Optional[float] = None) -> str:
        """``GET /metrics`` Prometheus text — the raw material of the
        fleet-merged ``/slo.json`` histograms."""
        status, _h, body = self.request("GET", "/metrics",
                                        timeout=timeout)
        return body if status == 200 and isinstance(body, str) else ""

    def models_doc(self, timeout: Optional[float] = None
                   ) -> Optional[dict]:
        status, _h, body = self.request("GET", "/models",
                                        timeout=timeout)
        return body if status == 200 and isinstance(body, dict) else None

    def slo_doc(self, timeout: Optional[float] = None) -> Optional[dict]:
        status, _h, body = self.request("GET", "/slo.json",
                                        timeout=timeout)
        return body if status == 200 and isinstance(body, dict) else None

    # -- KV-page transfer (docs/serving.md "Disaggregated
    # prefill/decode"): the one raw-bytes path in the client — page
    # blobs are binary wire format, not JSON ---------------------------------
    def fetch_pages(self, hashes=None, top: Optional[int] = None,
                    timeout: Optional[float] = None
                    ) -> Tuple[int, bytes]:
        """``GET /kv/pages`` → ``(status, blob)``.  ``hashes`` is an
        iterable of page digests (raw bytes or hex — the query string
        carries hex); ``top=K`` instead fetches the replica's K hottest
        cached pages (the drain pre-warm set).  Non-200 answers return
        the status with an empty blob; transport failures raise
        :class:`ReplicaUnavailable` like every other call."""
        if top is not None:
            path = f"/kv/pages?top={int(top)}"
        else:
            hx = ",".join(h if isinstance(h, str) else bytes(h).hex()
                          for h in (hashes or []))
            path = f"/kv/pages?hashes={hx}"
        req = urllib.request.Request(self.base_url + path, method="GET")
        t = self.timeout_s if timeout is None else float(timeout)
        try:
            with urllib.request.urlopen(req, timeout=t) as r:
                return r.status, r.read()
        except urllib.error.HTTPError as e:
            with e:
                e.read()
            return e.code, b""
        except (urllib.error.URLError, ConnectionError, TimeoutError,
                OSError) as e:
            raise ReplicaUnavailable(
                f"{self.base_url}: {type(e).__name__}: {e}") from e

    def put_pages(self, blob: bytes, timeout: Optional[float] = None
                  ) -> Tuple[int, object]:
        """``PUT /kv/pages`` → ``(status, doc)`` — ship a serialized
        page blob into the replica's prefix cache.  400 means the
        replica REJECTED the blob (geometry/weights-version/integrity);
        the caller falls back to local prefill, never errors the
        request."""
        req = urllib.request.Request(
            self.base_url + "/kv/pages", data=bytes(blob),
            headers={"Content-Type": "application/octet-stream"},
            method="PUT")
        t = self.timeout_s if timeout is None else float(timeout)
        try:
            with urllib.request.urlopen(req, timeout=t) as r:
                return r.status, self._parse(r.read())
        except urllib.error.HTTPError as e:
            with e:
                return e.code, self._parse(e.read())
        except (urllib.error.URLError, ConnectionError, TimeoutError,
                OSError) as e:
            raise ReplicaUnavailable(
                f"{self.base_url}: {type(e).__name__}: {e}") from e

    # -- dispatch ------------------------------------------------------------
    def generate(self, body: dict, timeout: Optional[float] = None
                 ) -> Tuple[int, object, float]:
        """Forward one ``POST /generate`` → ``(status, doc,
        retry_after_s)``.  ``retry_after_s`` is 0.0 unless the replica
        shed the request (429) — then it carries the replica's adaptive
        hint (the un-rounded body value when present, else the
        header)."""
        status, headers, doc = self.request("POST", "/generate", body,
                                            timeout=timeout)
        retry = 0.0
        if status == 429:
            if isinstance(doc, dict) and doc.get("retry_after_s"):
                retry = float(doc["retry_after_s"])
            else:
                try:
                    retry = float(headers.get("Retry-After", 1.0))
                except (TypeError, ValueError):
                    retry = 1.0
        return status, doc, retry

    def generate_stream(self, body: dict,
                        timeout: Optional[float] = None):
        """Forward one streaming ``POST /generate`` (``{"stream":
        true}`` body) → ``(status, frames_or_doc, retry_after_s)``.
        On 200, ``frames_or_doc`` is an ITERATOR of parsed NDJSON frame
        dicts — the connection stays open while the caller drains it,
        and a transport failure mid-stream raises
        :class:`ReplicaUnavailable` FROM THE ITERATOR (the router's
        resume-from-last-frame signal).  On any error status the
        connection is already drained and closed and ``frames_or_doc``
        is the parsed error body, matching :meth:`generate`'s shape."""
        data = json.dumps(body).encode()
        req = urllib.request.Request(
            self.base_url + "/generate", data=data,
            headers={"Content-Type": "application/json"},
            method="POST")
        t = self.timeout_s if timeout is None else float(timeout)
        try:
            resp = urllib.request.urlopen(req, timeout=t)
        except urllib.error.HTTPError as e:
            with e:
                doc = self._parse(e.read())
            retry = 0.0
            if e.code == 429:
                if isinstance(doc, dict) and doc.get("retry_after_s"):
                    retry = float(doc["retry_after_s"])
                else:
                    try:
                        retry = float(e.headers.get("Retry-After", 1.0))
                    except (TypeError, ValueError):
                        retry = 1.0
            return e.code, doc, retry

        except (urllib.error.URLError, ConnectionError, TimeoutError,
                OSError) as e:
            raise ReplicaUnavailable(
                f"{self.base_url}: {type(e).__name__}: {e}") from e

        def frames():
            try:
                with resp:
                    for raw in resp:
                        raw = raw.strip()
                        if not raw:
                            continue
                        try:
                            yield json.loads(raw)
                        except json.JSONDecodeError as e:
                            # a half-written line is a mid-stream cut,
                            # same failover signal as a dropped socket
                            raise ReplicaUnavailable(
                                f"{self.base_url}: truncated stream "
                                f"frame: {e}") from e
            except (ConnectionError, TimeoutError, OSError,
                    http.client.HTTPException) as e:
                raise ReplicaUnavailable(
                    f"{self.base_url}: {type(e).__name__}: {e}") from e

        return resp.status, frames(), 0.0

    # -- lifecycle ops (the coordinated-swap / drain fan-out) ---------------
    def stage(self, source: Optional[str] = None, version=None,
              timeout: Optional[float] = None) -> Tuple[int, object]:
        body = {}
        if source is not None:
            body["source"] = str(source)
        if version is not None:
            body["version"] = version
        status, _h, doc = self.request("POST", "/admin/stage", body,
                                       timeout=timeout)
        return status, doc

    def commit(self, token: str, timeout: Optional[float] = None
               ) -> Tuple[int, object]:
        status, _h, doc = self.request("POST", "/admin/commit",
                                       {"token": token}, timeout=timeout)
        return status, doc

    def abort(self, token: Optional[str] = None,
              timeout: Optional[float] = None) -> Tuple[int, object]:
        body = {} if token is None else {"token": token}
        status, _h, doc = self.request("POST", "/admin/abort", body,
                                       timeout=timeout)
        return status, doc

    def reload(self, source: Optional[str] = None, version=None,
               timeout: Optional[float] = None) -> Tuple[int, object]:
        body = {}
        if source is not None:
            body["source"] = str(source)
        if version is not None:
            body["version"] = version
        status, _h, doc = self.request("POST", "/admin/reload", body,
                                       timeout=timeout)
        return status, doc

    def drain(self, timeout: Optional[float] = None) -> Tuple[int, object]:
        status, _h, doc = self.request("POST", "/admin/drain", {},
                                       timeout=timeout)
        return status, doc
