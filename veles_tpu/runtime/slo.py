"""Rolling SLO windows: is the service meeting its latency target NOW.

The since-boot histograms in the registry answer "what happened ever";
an operator (and `/ready`) needs "what happened over the last minute" —
a cumulative distribution hides a fresh regression behind hours of good
history.  This module keeps a :class:`~.metrics.HistogramWindow` ring
over the serving latency histograms (TTFT, queue wait) and serves
windowed p50/p95/p99 plus **burn rate** against configurable targets at
``GET /slo.json`` (docs/observability.md "Rolling SLO windows").

Burn rate is the standard error-budget consumption ratio: a target
"p99 TTFT <= X ms" grants a 1% budget of requests over X; burn =
(observed fraction over X in the window) / 1%.  Burn 1.0 = exactly on
target, 2.0 = burning budget twice as fast as granted.  With
``root.common.observe.slo.degrade_ready`` on, a window whose burn
reaches ``slo.burn_threshold`` flips ``GET /ready`` to 503 so a load
balancer sheds traffic *before* the tail melts — the window length IS
the "sustained" filter (one slow request cannot trip it; a minimum
sample count guards cold starts).

Everything here is host-side and jax-free: windows snapshot registry
histograms, nothing touches traced scope (the analyzer's VT103 gate).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional

from ..config import root
from .metrics import (HistogramWindow, fraction_over,
                      quantile_from_cumulative, registry)

#: slo key -> the registry histogram its window snapshots
_TRACKED = (
    ("ttft", "vt_request_ttft_seconds"),
    ("queue_wait", "vt_request_queue_wait_seconds"),
)

#: the percentile every target key refers to (p99 — the budget is 1%).
_TARGET_Q = 0.99

#: a window with fewer samples than this can never "burn": the first
#: request after boot must not 503 the whole server.
_MIN_COUNT = 10


class SloTracker:
    """Windowed latency views + burn-rate evaluation over the process
    registry.  ``clock`` / ``window_s`` / ``slices`` are injectable for
    deterministic tests; production uses :func:`slo_tracker` which reads
    ``root.common.observe.slo.*`` once at first use."""

    def __init__(self, *, window_s: Optional[float] = None,
                 slices: Optional[int] = None,
                 targets_ms: Optional[Dict[str, float]] = None,
                 burn_threshold: Optional[float] = None,
                 clock=time.monotonic):
        slo = root.common.observe.slo
        self.window_s = float(window_s if window_s is not None
                              else slo.get("window_s", 60.0))
        self.slices = int(slices if slices is not None
                          else slo.get("slices", 12))
        self.burn_threshold = float(
            burn_threshold if burn_threshold is not None
            else slo.get("burn_threshold", 2.0))
        if targets_ms is None:
            # literal reads: the VK3xx drift rule cross-references them
            # against the config.py declarations and the docs table
            targets_ms = {
                "ttft": slo.get("ttft_p99_ms", 0.0),
                "queue_wait": slo.get("queue_wait_p99_ms", 0.0),
            }
        self.targets_ms: Dict[str, float] = {
            key: float(targets_ms.get(key, 0.0) or 0.0)
            for key, _m in _TRACKED}
        reg = registry()
        self._g_burn = reg.gauge(
            "vt_slo_burn_rate",
            "error-budget burn rate over the rolling window, by slo "
            "(fraction of requests over the p99 target / the 1% budget; "
            "0 when no target is configured)", labels=("slo",))
        self.windows: Dict[str, HistogramWindow] = {
            key: HistogramWindow((lambda m=metric: reg.get(m)),
                                 self.window_s, self.slices, clock=clock)
            for key, metric in _TRACKED}

    def tick(self) -> None:
        """Rotate every window ring (cheap, idempotent) — called from
        the decode scheduler tick and any endpoint read.  When a slice
        actually rotated, the derived burn-rate gauges are recomputed
        too, so a bare ``/metrics`` scrape sees a live
        ``vt_slo_burn_rate`` without anything ever reading
        ``/slo.json``."""
        rotated = False
        for w in self.windows.values():
            rotated = w.tick() or rotated
        if rotated:
            for key, _metric in _TRACKED:
                self._one(key)          # sets the burn gauge per slo

    def _one(self, key: str) -> dict:
        w = self.windows[key]
        _hist, pairs, count, total = w.delta()
        out = {"count": int(count),
               "sum_seconds": round(float(total), 6)}
        for q in (0.5, 0.95, 0.99):
            out[f"p{int(q * 100)}_ms"] = round(
                1e3 * quantile_from_cumulative(pairs, q), 3)
        target_ms = self.targets_ms.get(key, 0.0)
        out["target_p99_ms"] = target_ms
        if target_ms > 0:
            frac = fraction_over(pairs, target_ms / 1e3)
            burn = frac / (1.0 - _TARGET_Q)
            out["frac_over_target"] = round(frac, 5)
            out["burn_rate"] = round(burn, 3)
            out["burning"] = (burn >= self.burn_threshold
                              and count >= _MIN_COUNT)
            self._g_burn.labels(slo=key).set(burn)
        else:
            out["frac_over_target"] = 0.0
            out["burn_rate"] = 0.0
            out["burning"] = False
            self._g_burn.labels(slo=key).set(0.0)
        return out

    def doc(self) -> dict:
        """The ``GET /slo.json`` body: windowed percentiles + burn per
        tracked latency, and whether /ready degradation would fire."""
        metrics = {key: self._one(key) for key, _m in _TRACKED}
        burning = any(m["burning"] for m in metrics.values())
        return {
            "window_s": self.window_s,
            "slices": self.slices,
            "burn_threshold": self.burn_threshold,
            "metrics": metrics,
            "burning": burning,
            "degrade_ready": bool(
                root.common.observe.slo.get("degrade_ready", False)),
        }

    def max_burn(self) -> float:
        """Worst burn rate across the tracked SLOs right now — the
        admission controller's sensor (runtime/admission.py).  A window
        with fewer than the minimum sample count contributes 0, for the
        same reason one slow request after boot must not 503 the server:
        it must not slam the admission window shut either."""
        worst = 0.0
        for key, _m in _TRACKED:
            m = self._one(key)
            if m["count"] >= _MIN_COUNT:
                worst = max(worst, m["burn_rate"])
        return worst

    def burning(self) -> bool:
        """Any tracked SLO at/over the burn threshold right now (with
        enough window samples to mean it)."""
        return any(self._one(key)["burning"] for key, _m in _TRACKED)

    def degrading(self) -> bool:
        """True when /ready should answer 503: degradation enabled AND
        a window is burning."""
        if not bool(root.common.observe.slo.get("degrade_ready", False)):
            return False
        return self.burning()


_TRACKER_LOCK = threading.Lock()
_TRACKER: Optional[SloTracker] = None  # guarded-by: _TRACKER_LOCK


def slo_tracker() -> SloTracker:
    """THE process SLO tracker (what ``GET /slo.json`` renders), built
    from ``root.common.observe.slo.*`` at first use."""
    global _TRACKER
    with _TRACKER_LOCK:
        if _TRACKER is None:
            _TRACKER = SloTracker()
        return _TRACKER


def reset_slo_tracker() -> None:
    """Drop the process tracker so the next :func:`slo_tracker` re-reads
    config — a test/config-reload hook, not a serving-path call."""
    global _TRACKER
    with _TRACKER_LOCK:
        _TRACKER = None
