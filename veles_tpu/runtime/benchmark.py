"""Device benchmark & compute-power rating.

Reference parity: the gemm DeviceBenchmark unit
(veles/accelerated_units.py:706-824) served two roles — (a) OpenCL
block-size autotuning persisted to ``devices/device_infos.json``
(veles/backends.py:672-731), (b) a slave ``computing_power`` rating
(1000/gemm-time, veles/accelerated_units.py:843-858) used by the master for
load balancing (veles/client.py:308-313).

TPU redesign: XLA owns tiling, so (a) becomes a *measurement* sweep —
gemm wall time / achieved TFLOPS per (size, dtype), persisted per device
kind (the analog of the device-info DB).  (b) survives as the same scalar
rating so higher layers (ensemble/GA job farming) can weight hosts by
throughput.
"""

from __future__ import annotations

import json
import os
import time
from typing import Dict, Optional, Sequence

import numpy as np

from ..config import root
from ..logger import Logger

# Reference benchmarked one size=3001 gemm (veles/backends.py:695: dtype
# sweep at size 3001); we sweep MXU-aligned sizes instead.
DEFAULT_SIZES = (1024, 2048, 4096)
DEFAULT_DTYPES = ("float32", "bfloat16")


def _gemm_seconds(n: int, dtype: str, reps: int) -> float:
    import jax
    import jax.numpy as jnp

    x = jnp.asarray(np.random.default_rng(0).standard_normal((n, n)),
                    jnp.dtype(dtype))

    @jax.jit
    def gemm_chain(a, b, k):
        # Chain k dependent gemms so per-call dispatch latency amortizes;
        # the final scalar read forces a full queue drain
        # (block_until_ready alone is unreliable over the axon tunnel —
        # see bench.py).
        def body(_, acc):
            return acc @ b
        out = jax.lax.fori_loop(0, k, body, a)
        return jnp.sum(out[0, :1])

    chain = 128  # long chain amortizes dispatch/tunnel round-trip latency
    float(gemm_chain(x, x, chain))  # compile + warm
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        float(gemm_chain(x, x, chain))
        best = min(best, (time.perf_counter() - t0) / chain)
    return best


class DeviceBenchmark(Logger):
    """Measure gemm throughput on the current default device."""

    def __init__(self, sizes: Sequence[int] = DEFAULT_SIZES,
                 dtypes: Sequence[str] = DEFAULT_DTYPES, reps: int = 3):
        self.sizes = tuple(sizes)
        self.dtypes = tuple(dtypes)
        self.reps = reps

    def run(self) -> Dict:
        import jax
        dev = jax.devices()[0]
        entries = []
        for dtype in self.dtypes:
            for n in self.sizes:
                secs = _gemm_seconds(n, dtype, self.reps)
                tflops = 2.0 * n ** 3 / secs / 1e12
                entries.append({"size": n, "dtype": dtype,
                                "seconds": secs, "tflops": tflops})
                self.info("gemm %dx%d %s: %.3f ms, %.2f TFLOPS",
                          n, n, dtype, secs * 1e3, tflops)
        info = {
            "device_kind": dev.device_kind,
            "platform": dev.platform,
            "results": entries,
            "computing_power": self.computing_power(entries),
        }
        return info

    @staticmethod
    def computing_power(entries) -> float:
        """Reference rating: 1000 / gemm-time on the largest f32-equivalent
        problem (veles/accelerated_units.py:853-858: 1000/time units)."""
        best = max((e for e in entries), key=lambda e: e["size"] * (
            2 if e["dtype"] == "float32" else 1))
        return 1000.0 / best["seconds"]


def resolve_peak_tflops() -> float:
    """The peak-flops denominator for MFU (docs/observability.md
    "Goodput & MFU"): the ``root.common.observe.peak_tflops`` override
    when set, else the best rate this device kind ever measured in the
    GEMM calibration DB (:func:`benchmark_device` persists it), else
    0.0 — "unknown", which every MFU consumer reports as 0 rather than
    inventing a denominator.  Never triggers a measurement itself: an
    MFU gauge must not cost a multi-second GEMM sweep mid-serve."""
    override = float(root.common.observe.get("peak_tflops", 0.0) or 0.0)
    if override > 0:
        return override
    try:
        import jax
        kind = jax.devices()[0].device_kind
    except Exception:
        return 0.0
    info = load_device_infos().get(kind) or {}
    rates = [float(e.get("tflops", 0.0))
             for e in info.get("results", ()) or ()]
    return max(rates) if rates else 0.0


def mfu_fraction(flops: float, wall_s: float, peak_tflops: float) -> float:
    """Model FLOPs utilization: achieved flops/s over the measured peak.
    0.0 whenever any input is unknown/degenerate — an MFU of 0 reads as
    "not measured", never as a fake 100%."""
    if flops <= 0 or wall_s <= 0 or peak_tflops <= 0:
        return 0.0
    return (flops / wall_s) / (peak_tflops * 1e12)


def epoch_goodput(flops_per_step: float, steps: float, wall_s: float,
                  peak_tflops: Optional[float] = None) -> Dict:
    """Goodput arithmetic for one training epoch, factored pure so the
    MFU math is testable with known flops and a fake clock's wall time:
    achieved flops/s over whatever wall the caller passes, and MFU
    against the measured peak.  The Trainer passes the TRAIN-phase wall
    (loader data waits included; eval and snapshot phases excluded —
    the ``vt_train_phase_seconds`` histogram breaks those out)."""
    if peak_tflops is None:
        peak_tflops = resolve_peak_tflops()
    total = float(flops_per_step) * float(steps)
    fps = total / wall_s if wall_s > 0 and total > 0 else 0.0
    return {
        "flops_per_step": float(flops_per_step),
        "steps": float(steps),
        "wall_s": float(wall_s),
        "flops_per_sec": fps,
        "peak_tflops": float(peak_tflops),
        "mfu": mfu_fraction(total, wall_s, peak_tflops),
    }


def device_info_path(cache_dir: Optional[str] = None) -> str:
    d = cache_dir or root.common.cache_dir
    return os.path.join(d, "device_infos.json")


def load_device_infos(cache_dir: Optional[str] = None) -> Dict:
    path = device_info_path(cache_dir)
    if os.path.exists(path):
        with open(path) as f:
            return json.load(f)
    return {}


def update_device_info(kind: str, mutate, cache_dir: Optional[str] = None
                       ) -> str:
    """Atomic read-modify-write of one device-kind record. Concurrent
    trainers/benchmarks (multi-process GA/ensemble pools, multi-host
    launches) share this DB; an unlocked load→save would clobber entries
    written in between. Writers serialize on a SIDECAR lock file (the DB
    file itself is replaced by rename, so locking its inode would race),
    and the tmp-write + os.replace keeps the DB complete at every instant
    for lock-free readers (load_device_infos)."""
    import fcntl
    path = device_info_path(cache_dir)
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path + ".lock", "w") as lockf:
        fcntl.flock(lockf, fcntl.LOCK_EX)
        try:
            infos = load_device_infos(cache_dir)
        except json.JSONDecodeError:  # pre-rename-era torn file
            infos = {}
        info = infos.get(kind, {"device_kind": kind})
        mutate(info)
        infos[kind] = info
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(infos, f, indent=1, sort_keys=True)
        os.replace(tmp, path)
    return path


def save_device_info(info: Dict, cache_dir: Optional[str] = None) -> str:
    """Persist per device kind — the analog of the reference's
    devices/device_infos.json block-size DB. Merges under the DB lock so
    concurrent writers of other keys are not clobbered."""
    return update_device_info(info["device_kind"],
                              lambda rec: rec.update(info), cache_dir)


def benchmark_device(cache_dir: Optional[str] = None, refresh: bool = False,
                     **kw) -> Dict:
    """Cached rating lookup (reference re-measured every 120 s on slaves;
    device kind is stable per process here, so cache on disk keyed by kind
    and refresh on demand)."""
    import jax
    kind = jax.devices()[0].device_kind
    if not refresh:
        cached = load_device_infos(cache_dir).get(kind)
        if cached:
            return cached
    info = DeviceBenchmark(**kw).run()
    save_device_info(info, cache_dir)
    return info
