"""Batch job lane: bulk offline inference over idle fleet capacity.

The priority/preemption/admission stack (docs/serving.md "Overload
survival") and the fleet router only *shed* load — the troughs between
interactive bursts leave slots, pages and compiled programs idle.  This
module fills them (docs/serving.md "Batch lane"): a **job** is a set of
prompts plus sampling params; the manager shards it into one engine
request per prompt and dispatches them with ``"batch": true`` — the
engine's trough-filler class below every interactive priority, admitted
only while headroom and SLO burn allow and preempted first the instant
interactive traffic arrives.

Durability is the core contract.  Every job lives in a directory under
the store root — a committed ``manifest.json`` plus one result file per
completed prompt — and all writes go through the snapshotter's
tmp-fsync-rename helpers (``_commit_bytes``; the VR704 lint rule pins
the idiom here too).  A crash, drain, preemption or replica ejection
therefore never loses completed work: a restarted manager reloads the
manifests, rebuilds each job's done-set from the result files on disk,
and re-enqueues only the prompts without a committed result.  Because
every prompt carries its own derived seed (``seed + index``) and the
engine's preempt/harvest/resume path is bitwise-deterministic, a resumed
or failed-over job produces byte-identical results to an uninterrupted
run — tests/test_chaos.py kills a replica mid-job to pin exactly that.

Dispatch is pluggable: the fleet router's ``handle_generate`` (the
fleet-level job API, with idempotent failover across replicas) or a
single :class:`~.restful.RestfulServer`'s local adapter — both return
the ``(status, doc, headers)`` triple.  In-flight dispatches register in
the ``_inflight`` ledger (the ``job-slots`` resource the VR701 pairing
rule tracks): acquire before the dispatch, release on result, permanent
failure, cancel and shutdown — a leaked entry would overstate
``vt_job_prompts_inflight`` and wedge the cancel path's accounting.

REST surface (served by both the fleet server and a single replica):
``POST /jobs`` submit → ``GET /jobs/<id>`` status →
``GET /jobs/<id>/results`` paged results, ``DELETE /jobs/<id>`` cancel;
``GET /jobs`` lists, and the fleet merges :meth:`JobManager.summary`
into ``/fleet.json``.
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
import uuid
from typing import Callable, Dict, List, Optional, Tuple

from ..config import root
from ..logger import Logger
from .metrics import registry
from .snapshotter import _commit_bytes, _fsync_dir

#: spec keys a ``POST /jobs`` body may carry (anything else is a 400 —
#: a typoed ``"temprature"`` must not silently decode greedy).
_SPEC_KEYS = frozenset({
    "prompts", "prompt_file", "steps", "temperature", "top_k", "top_p",
    "eos_id", "seed",
})

#: terminal job states (no work left to schedule).
_TERMINAL = ("done", "cancelled")


class JobError(ValueError):
    """Malformed job spec or unknown job id (the REST 400/404 path)."""


class _Job:
    """In-memory twin of one persisted job directory."""

    __slots__ = ("id", "prompts", "params", "seed", "state", "created",
                 "done_idx", "failed_idx", "error_by_idx")

    def __init__(self, job_id: str, prompts: List[List[int]],
                 params: dict, seed: int, state: str = "running",
                 created: float = 0.0):
        self.id = job_id
        self.prompts = prompts
        self.params = params            # steps/temperature/top_k/...
        self.seed = int(seed)
        # mutable progress state: the owning manager's _lock guards
        # every post-construction touch (the _Job itself carries no
        # lock — load_all builds free-standing instances)
        self.state = state
        self.created = float(created)
        self.done_idx: set = set()
        self.failed_idx: set = set()
        self.error_by_idx: dict = {}

    def request_body(self, idx: int) -> dict:
        """The ``/generate`` body for prompt ``idx`` — always
        ``batch: true`` (the engine's trough class) and a per-prompt
        seed derived from the job seed, so the result is a pure
        function of (job spec, index): any replica, any preemption
        history, any retry produces the same bytes."""
        body = {"prompt": [self.prompts[idx]],
                "steps": self.params["steps"],
                "seed": self.seed + idx,
                "batch": True}
        for k in ("temperature", "top_k", "top_p", "eos_id"):
            if self.params.get(k) is not None:
                body[k] = self.params[k]
        return body

    def manifest(self) -> dict:
        return {"id": self.id, "state": self.state,
                "created": self.created, "seed": self.seed,
                "n_prompts": len(self.prompts),
                "params": self.params, "prompts": self.prompts}


class JobStore:
    """Durable job persistence: one directory per job under ``base``,
    holding a committed ``manifest.json`` and ``results/NNNNNN.json``
    per finished prompt.  Every write stages through the snapshotter's
    tmp-fsync-rename helper — a crash leaves the previous committed
    state, never a torn file a resume would trust (VR704)."""

    def __init__(self, base: str):
        self.base = str(base)
        os.makedirs(self.base, exist_ok=True)

    def _job_dir(self, job_id: str) -> str:
        return os.path.join(self.base, job_id)

    def _result_path(self, job_id: str, idx: int) -> str:
        return os.path.join(self._job_dir(job_id), "results",
                            f"{int(idx):06d}.json")

    def commit_manifest(self, job: _Job) -> None:
        d = self._job_dir(job.id)
        os.makedirs(os.path.join(d, "results"), exist_ok=True)
        _commit_bytes(os.path.join(d, "manifest.json"),
                      json.dumps(job.manifest()).encode())
        _fsync_dir(d)

    def commit_result(self, job_id: str, idx: int, doc: dict) -> None:
        path = self._result_path(job_id, idx)
        _commit_bytes(path, json.dumps(doc).encode())
        _fsync_dir(os.path.dirname(path))

    def has_result(self, job_id: str, idx: int) -> bool:
        return os.path.exists(self._result_path(job_id, idx))

    def read_result(self, job_id: str, idx: int) -> Optional[dict]:
        try:
            with open(self._result_path(job_id, idx)) as f:
                return json.load(f)
        except (OSError, json.JSONDecodeError):
            return None

    def load_all(self) -> List[_Job]:
        """Rebuild every persisted job: the manifest names the prompts
        and params; the done-set is recomputed from the result files
        actually committed — the on-disk results ARE the progress
        record, so a crash between a result commit and any counter
        update can never double-run or drop a prompt."""
        jobs: List[_Job] = []
        try:
            entries = sorted(os.listdir(self.base))
        except OSError:
            return jobs
        for name in entries:
            mpath = os.path.join(self.base, name, "manifest.json")
            try:
                with open(mpath) as f:
                    m = json.load(f)
            except (OSError, json.JSONDecodeError):
                continue        # half-created dir (pre-first-commit)
            job = _Job(m["id"], m["prompts"], m["params"],
                       m.get("seed", 0), state=m.get("state", "running"),
                       created=m.get("created", 0.0))
            for idx in range(len(job.prompts)):
                doc = self.read_result(job.id, idx) \
                    if self.has_result(job.id, idx) else None
                if doc is None:
                    continue
                job.done_idx.add(idx)
                if "error" in doc:
                    job.failed_idx.add(idx)
                    job.error_by_idx[idx] = doc["error"]
            jobs.append(job)
        return jobs


class JobManager(Logger):
    """Shards jobs into per-prompt batch-class requests and drives them
    through ``dispatch`` — ``FleetRouter.handle_generate`` or a single
    replica's local adapter, both ``body -> (status, doc, headers)``.

    Worker threads pull ``(job_id, idx)`` items from the work deque.
    A 429 (trough closed / replica backpressure) requeues the item and
    backs off by the server's Retry-After hint — batch work *waits out*
    interactive bursts, it never competes with them.  A 400 is a
    permanent per-prompt failure (recorded as that prompt's result); a
    5xx/transport failure requeues with backoff.  Results commit to the
    durable store exactly once per prompt — the done-set check runs
    before every dispatch, so retries and resumes can't double-commit.
    """

    def __init__(self, store_dir: str,
                 dispatch: Callable[[dict], Tuple[int, object, tuple]],
                 *, workers: Optional[int] = None,
                 retry_s: Optional[float] = None,
                 max_prompts: Optional[int] = None):
        jobs_cfg = root.common.serve.jobs
        self._dispatch = dispatch
        self._store = JobStore(store_dir)
        self.workers = max(1, int(jobs_cfg.get("workers", 2)
                                  if workers is None else workers))
        self.retry_s = float(jobs_cfg.get("retry_s", 0.25)
                             if retry_s is None else retry_s)
        self.max_prompts = int(jobs_cfg.get("max_prompts", 100_000)
                               if max_prompts is None else max_prompts)
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._jobs: Dict[str, _Job] = {}        # guarded-by: self._lock
        self._work: collections.deque = collections.deque()  # guarded-by: self._lock
        self._inflight: Dict[Tuple[str, int], float] = {}  # guarded-by: self._lock
        self._stop_evt = threading.Event()
        self._threads: List[threading.Thread] = []
        self._counts = {"submitted": 0, "completed": 0, "cancelled": 0}  # guarded-by: self._lock
        reg = registry()
        self._m_submitted = reg.counter(
            "vt_jobs_submitted_total", "batch jobs accepted by POST "
            "/jobs (resumed-from-disk jobs not re-counted)")
        self._m_completed = reg.counter(
            "vt_jobs_completed_total",
            "batch jobs whose every prompt reached a terminal result")
        self._m_cancelled = reg.counter(
            "vt_jobs_cancelled_total", "batch jobs cancelled via "
            "DELETE /jobs/<id> before completing")
        self._g_inflight = reg.gauge(
            "vt_job_prompts_inflight",
            "per-prompt batch requests currently dispatched and "
            "awaiting a replica's answer (the job-slots ledger depth)")
        self._g_inflight.set(0)
        # crash/preemption resume: reload persisted jobs, re-enqueue
        # exactly the prompts without a committed result
        for job in self._store.load_all():
            self._jobs[job.id] = job
            if job.state not in _TERMINAL:
                missing = [i for i in range(len(job.prompts))
                           if i not in job.done_idx]
                if not missing:
                    self._finish_job_locked(job)
                    self._store.commit_manifest(job)
                else:
                    self._work.extend((job.id, i) for i in missing)

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "JobManager":
        if self._threads:
            return self
        self._stop_evt.clear()
        for i in range(self.workers):
            t = threading.Thread(target=self._worker, daemon=True,
                                 name=f"job-worker-{i}")
            t.start()
            self._threads.append(t)
        return self

    def stop(self):
        """Stop scheduling — in-flight dispatches finish or fail on
        their own connections; their committed results survived either
        way, so a restart resumes from exactly this point."""
        self._stop_evt.set()
        with self._lock:                # the condition SHARES this lock
            self._cv.notify_all()
        for t in self._threads:
            t.join(timeout=5.0)
        self._threads.clear()
        with self._lock:
            for key in list(self._inflight):
                self._release_job_slot_locked(key)

    # -- the job-slots ledger (analysis registry RESOURCE_PAIRS) -------------
    def _acquire_job_slot(self, key: Tuple[str, int]) -> None:
        """Register one dispatched prompt in the in-flight ledger.
        Every acquire MUST reach :meth:`_release_job_slot` on result,
        permanent failure, cancel and shutdown paths (VR701)."""
        with self._lock:
            self._inflight[key] = time.monotonic()
            self._g_inflight.set(len(self._inflight))

    def _release_job_slot(self, key: Tuple[str, int]) -> None:
        """Drop one prompt from the in-flight ledger (idempotent — the
        cancel and shutdown sweeps race the worker's own finally)."""
        with self._lock:
            self._release_job_slot_locked(key)

    def _release_job_slot_locked(self, key: Tuple[str, int]) -> None:  # requires-lock: self._lock
        self._inflight.pop(key, None)
        self._g_inflight.set(len(self._inflight))

    # -- submission / query API ----------------------------------------------
    def submit(self, spec: dict) -> dict:
        """Validate + persist one job, enqueue its prompts, return the
        status doc.  The manifest commits BEFORE the first dispatch:
        from the client's 200 onward the job survives any crash."""
        if not isinstance(spec, dict):
            raise JobError("job spec must be a JSON object")
        unknown = set(spec) - _SPEC_KEYS
        if unknown:
            raise JobError(f"unknown job spec keys: {sorted(unknown)}")
        prompts = self._load_prompts(spec)
        params = self._validate_params(spec)
        seed = int(spec.get("seed", 0))
        job = _Job(uuid.uuid4().hex[:12], prompts, params, seed,
                   created=time.time())
        self._store.commit_manifest(job)
        with self._lock:                # the condition SHARES this lock
            self._jobs[job.id] = job
            self._counts["submitted"] += 1
            self._work.extend((job.id, i) for i in range(len(prompts)))
            self._cv.notify_all()
        self._m_submitted.inc()
        return self.status(job.id)

    def _load_prompts(self, spec: dict) -> List[List[int]]:
        if ("prompts" in spec) == ("prompt_file" in spec):
            raise JobError(
                'job spec needs exactly one of "prompts" (inline) or '
                '"prompt_file" (server-side JSON path)')
        if "prompt_file" in spec:
            path = str(spec["prompt_file"])
            try:
                with open(path) as f:
                    prompts = json.load(f)
            except (OSError, json.JSONDecodeError) as e:
                raise JobError(
                    f"prompt_file {path!r} unreadable: {e}") from None
        else:
            prompts = spec["prompts"]
        if not isinstance(prompts, list) or not prompts:
            raise JobError("prompts must be a non-empty list of "
                           "token-id lists")
        if len(prompts) > self.max_prompts:
            raise JobError(f"{len(prompts)} prompts exceeds "
                           f"serve.jobs.max_prompts {self.max_prompts}")
        out: List[List[int]] = []
        for i, p in enumerate(prompts):
            if not isinstance(p, (list, tuple)) or not p:
                raise JobError(
                    f"prompt {i} must be a non-empty token-id list")
            try:
                row = [int(t) for t in p]
            except (TypeError, ValueError):
                raise JobError(
                    f"prompt {i} holds non-integer token ids") from None
            if any(t != float(orig) for t, orig in zip(row, p)):
                raise JobError(
                    f"prompt {i} holds non-integer token ids")
            out.append(row)
        return out

    @staticmethod
    def _validate_params(spec: dict) -> dict:
        steps = int(spec.get("steps", 16))
        if steps < 1:
            raise JobError(f"steps must be >= 1, got {steps}")
        params = {"steps": steps}
        for k in ("temperature", "top_k", "top_p", "eos_id"):
            if spec.get(k) is not None:
                params[k] = spec[k]
        return params

    def _get(self, job_id: str) -> _Job:
        with self._lock:
            job = self._jobs.get(job_id)
        if job is None:
            raise KeyError(f"no such job: {job_id}")
        return job

    def status(self, job_id: str) -> dict:
        job = self._get(job_id)
        with self._lock:
            done = len(job.done_idx)
            failed = len(job.failed_idx)
            running = sum(1 for j, _i in self._inflight if j == job_id)
            state = job.state
        total = len(job.prompts)
        return {
            "id": job.id, "state": state, "created": job.created,
            "prompts": total,
            "queued": max(total - done - running, 0),
            "running": running, "done": done, "failed": failed,
        }

    def results(self, job_id: str, offset: int = 0,
                limit: Optional[int] = None) -> dict:
        """One page of per-prompt results, in prompt order.  The store
        is the source of truth — only committed results appear, so a
        reader never sees work a crash could retract."""
        job = self._get(job_id)
        jobs_cfg = root.common.serve.jobs
        page = int(jobs_cfg.get("page_limit", 256)
                   if limit is None else limit)
        offset = max(int(offset), 0)
        total = len(job.prompts)
        out = []
        for idx in range(offset, min(offset + max(page, 0), total)):
            doc = self._store.read_result(job.id, idx)
            if doc is not None:
                out.append(doc)
        next_offset = offset + max(page, 0)
        return {"id": job.id, "offset": offset, "prompts": total,
                "results": out,
                **({"next_offset": next_offset}
                   if next_offset < total else {})}

    def cancel(self, job_id: str) -> dict:
        """Cancel: drop the job's queued work immediately and mark it
        terminal.  Dispatches already on the wire retire or fail on
        their replicas (their late answers are discarded below); the
        engine's lowest-class slots they occupy are reclaimed the
        moment any interactive request wants them — preemption, not
        cancellation, is the instant-yield path."""
        job = self._get(job_id)
        with self._lock:
            already = job.state in _TERMINAL
            if not already:
                job.state = "cancelled"
                self._counts["cancelled"] += 1
                self._work = collections.deque(
                    (j, i) for j, i in self._work if j != job_id)
                for key in [k for k in self._inflight
                            if k[0] == job_id]:
                    self._release_job_slot_locked(key)
        if not already:
            self._m_cancelled.inc()
            self._store.commit_manifest(job)
        return self.status(job_id)

    def list_jobs(self) -> dict:
        with self._lock:
            ids = sorted(self._jobs, key=lambda j: self._jobs[j].created)
        return {"jobs": [self.status(j) for j in ids]}

    def summary(self) -> dict:
        """The fleet-level view ``/fleet.json`` merges: job counts by
        state plus the live work backlog."""
        with self._lock:
            states: Dict[str, int] = {}
            for job in self._jobs.values():
                states[job.state] = states.get(job.state, 0) + 1
            return {
                "total": len(self._jobs),
                "by_state": states,
                "prompts_pending": len(self._work),
                "prompts_inflight": len(self._inflight),
                **{k: v for k, v in self._counts.items()},
            }

    def wait(self, job_id: str, timeout_s: float = 60.0) -> bool:
        """Block until the job is terminal (poll-based: terminality is
        a disk-backed property, not an in-memory event)."""
        deadline = time.monotonic() + float(timeout_s)
        while time.monotonic() < deadline:
            with self._lock:
                job = self._jobs.get(job_id)
                if job is not None and job.state in _TERMINAL:
                    return True
            time.sleep(0.02)
        return False

    # -- workers (host loop; analysis registry HOST_LOOP_ROOTS) --------------
    def _next_item(self) -> Optional[Tuple[str, int]]:
        with self._lock:                # the condition SHARES this lock
            while not self._stop_evt.is_set():
                if self._work:
                    return self._work.popleft()
                self._cv.wait(timeout=0.1)
        return None

    def _worker(self):
        """One dispatch worker: pure control plane — HTTP bodies in,
        committed result files out; it must never reach a traced-
        program builder (HOST_LOOP_ROOTS pins that)."""
        while not self._stop_evt.is_set():
            item = self._next_item()
            if item is None:
                return
            try:
                self._run_one(item)
            except Exception:  # noqa: BLE001 — a poisoned item must
                # not kill the worker pool; the item was released and
                # requeued (or recorded failed) by _run_one's own paths
                self.exception("job worker failed on %s", item)

    def _requeue(self, key: Tuple[str, int], delay_s: float):
        """Put a not-yet-terminal prompt back (at the back: FIFO over
        the remaining work) after releasing its slot, and back off so
        a closed trough is polled, not hammered."""
        self._release_job_slot(key)
        with self._lock:                # the condition SHARES this lock
            job = self._jobs.get(key[0])
            if job is not None and job.state not in _TERMINAL:
                self._work.append(key)
                self._cv.notify()
        if delay_s > 0:
            self._stop_evt.wait(timeout=min(float(delay_s), 2.0))

    def _run_one(self, key: Tuple[str, int]):
        job_id, idx = key
        with self._lock:
            job = self._jobs.get(job_id)
            stale = (job is None or job.state in _TERMINAL
                     or idx in job.done_idx or key in self._inflight)
        if stale:
            return
        self._acquire_job_slot(key)
        requeued = False
        try:
            try:
                status, doc, _headers = self._dispatch(
                    job.request_body(idx))
            except Exception as e:  # noqa: BLE001 — transport-level
                # dispatch failure (router gone, local engine raising
                # unexpectedly): transient, retry
                self.warning("job %s prompt %d dispatch failed: %s",
                             job_id, idx, e)
                requeued = True
                self._requeue(key, self.retry_s)
                return
            if status == 200 and isinstance(doc, dict):
                rows = doc.get("tokens") or [[]]
                self._commit(job, idx,
                             {"index": idx, "tokens": rows[0]})
                return
            if status == 429:
                retry = self.retry_s
                if isinstance(doc, dict) and doc.get("retry_after_s"):
                    try:
                        retry = max(retry,
                                    float(doc["retry_after_s"]))
                    except (TypeError, ValueError):
                        pass
                requeued = True
                self._requeue(key, retry)
                return
            if status == 400:
                # the replica REJECTED the prompt (length/vocab/params):
                # permanent — record it as this prompt's terminal result
                err = doc.get("error") if isinstance(doc, dict) \
                    else str(doc)
                self._commit(job, idx,
                             {"index": idx, "error": str(err)})
                return
            # 5xx/503/504: the fleet layer already failed over where it
            # could — whatever is left is transient from here
            requeued = True
            self._requeue(key, self.retry_s)
        finally:
            # _requeue releases before re-appending (a re-appended item
            # may already be re-acquired by another worker — a second
            # release here would drop THAT worker's ledger entry)
            if not requeued:
                self._release_job_slot(key)

    def _commit(self, job: _Job, idx: int, doc: dict):
        """Exactly-once result commit: the durable write lands first,
        then the in-memory done-set — a crash between the two re-runs
        nothing (the resume scan trusts the disk, and the pre-dispatch
        done-check consults the same set)."""
        with self._lock:
            if job.state in _TERMINAL or idx in job.done_idx:
                return          # cancelled mid-flight / duplicate race
        self._store.commit_result(job.id, idx, doc)
        finished = False
        with self._lock:
            job.done_idx.add(idx)
            if "error" in doc:
                job.failed_idx.add(idx)
                job.error_by_idx[idx] = doc["error"]
            if job.state not in _TERMINAL \
                    and len(job.done_idx) >= len(job.prompts):
                self._finish_job_locked(job)
                finished = True
        if finished:
            self._store.commit_manifest(job)
            self._m_completed.inc()

    def _finish_job_locked(self, job: _Job) -> None:  # requires-lock: self._lock
        job.state = "done"
        self._counts["completed"] += 1


def handle_jobs_request(manager: Optional[JobManager], method: str,
                        path: str, body: Optional[dict]
                        ) -> Optional[Tuple[int, object]]:
    """Shared REST glue for the job API — both the fleet server and a
    single replica route ``/jobs*`` requests here.  Returns
    ``(status, doc)`` or None when ``path`` is not a jobs route (the
    caller falls through to its own 404)."""
    from urllib.parse import parse_qs, urlparse
    parsed = urlparse(path)
    parts = [p for p in parsed.path.split("/") if p]
    if not parts or parts[0] != "jobs":
        return None
    if manager is None:
        return 404, {"error": "no job manager attached (set "
                              "serve.jobs.dir; see docs/serving.md "
                              '"Batch lane")'}
    try:
        if method == "POST" and len(parts) == 1:
            return 200, manager.submit(body or {})
        if method == "GET" and len(parts) == 1:
            return 200, manager.list_jobs()
        if method == "GET" and len(parts) == 2:
            return 200, manager.status(parts[1])
        if method == "GET" and len(parts) == 3 \
                and parts[2] == "results":
            q = parse_qs(parsed.query)
            offset = int(q.get("offset", ["0"])[0])
            limit = q.get("limit")
            return 200, manager.results(
                parts[1], offset,
                None if limit is None else int(limit[0]))
        if method == "DELETE" and len(parts) == 2:
            return 200, manager.cancel(parts[1])
    except KeyError as e:
        return 404, {"error": str(e)}
    except (JobError, TypeError, ValueError) as e:
        return 400, {"error": str(e)}
    return 404, {"error": f"unknown jobs route {parsed.path}"}
