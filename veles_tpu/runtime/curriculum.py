"""Snapshot-phased curriculum runner — chained CLI training phases.

Productizes the pattern that cleared the hard induction bar
(configs/induction_lm64_curriculum.sh, BASELINE.md): train in phases,
each an ordinary CLI run with its own config overrides and seeds, each
restoring from the BEST snapshot any earlier phase produced. The
reference's closest machinery is the rollback-to-best + lr-drop policy
(Znicz docs manualrst_veles_algorithms.rst:164, here
runtime/decision.py); a curriculum generalizes it across runs: fresh
data/mixture/lr per phase while weights carry forward.

Spec file (JSON)::

    {
      "common": ["loader.n_train=2000"],        # overrides for every phase
      "phases": [
        {"overrides": ["loader.repeat_fraction=1.0",
                       "workflow.max_epochs=170"],
         "random_seed": 1},
        {"repeat": 5,                            # expand to 5 phases
         "overrides": ["workflow.max_epochs={budget}",
                       "workflow.optimizer_args.lr=0.0003",
                       "loader.data_seed={1000+i}"],
         "epochs_increment": 150,                # {budget} += this/phase
         "random_seed": "{i}"}
      ]
    }

Placeholders inside override strings / random_seed: ``{i}`` = 1-based
phase index, ``{budget}`` = a running epoch budget that starts at the
first phase's ``workflow.max_epochs`` and grows by ``epochs_increment``
per expanded phase, and ``{N+i}`` = integer N plus the phase index.
"""

from __future__ import annotations

import glob
import json
import os
import re
from typing import List, Optional, Sequence

from ..logger import Logger


class CurriculumError(RuntimeError):
    pass


def _subst(text: str, i: int, budget: int) -> str:
    def repl(m):
        expr = m.group(1)
        if expr == "i":
            return str(i)
        if expr == "budget":
            return str(budget)
        mm = re.fullmatch(r"(\d+)\+i", expr)
        if mm:
            return str(int(mm.group(1)) + i)
        raise CurriculumError(f"unknown curriculum placeholder {{{expr}}}")
    return re.sub(r"\{([^}]+)\}", repl, text)


def expand_phases(spec: dict) -> List[dict]:
    """Resolve repeats and placeholders into a flat phase list."""
    if not spec.get("phases"):
        raise CurriculumError("curriculum spec has no phases")
    budget = 0
    for ov in spec["phases"][0].get("overrides", []):
        m = re.fullmatch(r"workflow\.max_epochs=(\d+)", ov)
        if m:
            budget = int(m.group(1))
    out = []
    i = 0
    for phase in spec.get("phases", []):
        for _ in range(int(phase.get("repeat", 1))):
            i += 1
            budget += int(phase.get("epochs_increment", 0))
            ovs = [_subst(o, i, budget)
                   for o in (list(spec.get("common", []))
                             + list(phase.get("overrides", [])))]
            seed = phase.get("random_seed")
            if isinstance(seed, str):
                seed = int(_subst(seed, i, budget))
            out.append({"index": i, "overrides": ovs,
                        "random_seed": seed})
    if not out:
        raise CurriculumError("curriculum spec has no phases")
    return out


class CurriculumRunner(Logger):
    """Run phases serially via ``python -m veles_tpu`` subprocesses
    (fresh interpreter state per phase, exactly like the hand-driven
    flow), threading the best snapshot forward."""

    def __init__(self, config: str, spec: dict, out_dir: str,
                 extra_argv: Sequence[str] = (), bar: Optional[float] = None,
                 initial_snapshot: Optional[str] = None,
                 default_seed: Optional[int] = None):
        self.config = config
        self.spec = spec
        self.out_dir = out_dir
        self.extra_argv = list(extra_argv)
        # optional early stop: best_value <= bar ends the curriculum
        self.bar = bar if bar is not None else spec.get("bar")
        # warm start: --snapshot seeds phase 1 (then per-phase bests)
        self.initial_snapshot = initial_snapshot
        # --random-seed forwarded to phases whose spec sets none
        self.default_seed = default_seed

    def _best_snapshot(self, phase_dir: str) -> Optional[str]:
        hits = glob.glob(os.path.join(phase_dir, "*_best.json"))
        if not hits:
            return None
        if len(hits) > 1:
            # a phase dir normally holds exactly one best snapshot; take
            # the newest and say so rather than an alphabetical accident
            hits.sort(key=os.path.getmtime)
            self.warning("phase dir %s holds %d *_best.json files; "
                         "using newest %s", phase_dir, len(hits),
                         os.path.basename(hits[-1]))
        return hits[-1]

    def run(self) -> dict:
        from ..parallel.pool import CliRunner
        os.makedirs(self.out_dir, exist_ok=True)
        phases = expand_phases(self.spec)
        # Serial phases: no chip contention, so DON'T pin subprocesses
        # to CPU — they inherit the parent platform (or an explicit
        # --platform in extra_argv).
        runner = CliRunner(n_workers=1, pin_cpu=False)
        best = None          # (value, phase index) — drives the bar
        best_snapshot = self.initial_snapshot
        best_snapshot_phase = None  # phase that wrote best_snapshot
        results = []
        for ph in phases:
            i = ph["index"]
            pdir = os.path.join(self.out_dir, f"p{i}")
            argv = [self.config, *ph["overrides"], *self.extra_argv,
                    "--snapshot-dir", pdir]
            seed = (ph["random_seed"] if ph["random_seed"] is not None
                    else self.default_seed)
            if seed is not None:
                argv += ["--random-seed", str(seed)]
            if best_snapshot:
                argv += ["--snapshot", best_snapshot]
            self.info("curriculum phase %d/%d%s: %s", i, len(phases),
                      f" (restore {best_snapshot})" if best_snapshot
                      else "", " ".join(argv))
            res = runner.run_jobs([argv])[0]
            if "error" in res:
                raise CurriculumError(
                    f"phase {i} failed: {res['error']}")
            results.append({"phase": i, **{k: res[k] for k in
                            ("best_value", "best_epoch", "epochs")
                            if k in res}})
            val = res.get("best_value")
            snap = self._best_snapshot(pdir)
            if val is not None and (best is None or val < best[0]):
                best = (val, i)
                if snap:
                    best_snapshot = snap
                    best_snapshot_phase = i
                else:
                    # value and snapshot advance atomically; a phase that
                    # improved the value but wrote no snapshot must not
                    # let the summary pair its value with an older,
                    # worse phase's snapshot silently
                    self.warning(
                        "phase %d improved best_value to %.4g but wrote "
                        "no *_best.json; best_snapshot stays at %s",
                        i, val,
                        f"phase {best_snapshot_phase}"
                        if best_snapshot_phase is not None
                        else (f"the initial snapshot {best_snapshot}"
                              if best_snapshot else "none"))
            elif best_snapshot is None and snap:
                best_snapshot = snap
                best_snapshot_phase = i
            if (self.bar is not None and best is not None
                    and best[0] <= float(self.bar)):
                self.info("bar %.4g reached at phase %d (%.4g) — stop",
                          float(self.bar), i, best[0])
                break
        summary = {
            "metric": "curriculum_best_value",
            "value": best[0] if best else None,
            "best_phase": best[1] if best else None,
            "phases_run": len(results),
            "phases": results,
            "best_snapshot": best_snapshot,
            "best_snapshot_phase": best_snapshot_phase,
        }
        with open(os.path.join(self.out_dir, "curriculum.json"),
                  "w") as f:
            json.dump(summary, f, indent=1)
        return summary
